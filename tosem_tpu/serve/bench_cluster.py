"""Cluster serving microbenchmarks — the multi-process closed-loop leg.

The first bench in the repo whose workload spans process trees: 2 node
agents × 2 replicas each behind the router tier
(:mod:`tosem_tpu.serve.cluster_serve`), interleaved A/B against the
single-process serve data plane on the SAME backend (per the
bench-noise protocol: both arms of a round share the host phase; the
absolute floors are min-of-rounds).

The acceptance leg is **failover**: a 16-client closed-loop fleet runs
THROUGH a mid-run node kill — the failure detector declares the node
dead, the controller re-places its replicas on the survivor under the
same ids, and routers re-admit in-flight requests from step 0. The
deterministic criteria are hard asserts: ZERO client-surfaced errors
(no logical request lost beyond transparent retries) and full
re-placement off the dead node. Throughput recovery is scored against
a same-shape CONTROL cluster deployment measured concurrently (the
only phase control that works here — see the leg's comment for the
measurement history), hard-failed only below a catastrophic 0.5x
bound, and recorded as a gated row so the perf gate tracks recovery
(vs the 1.0 baseline, standard threshold) release over release.

A non-gated parity leg deploys a ``sharding=(1, 2)`` replica (dp×tp
mesh in its own process, gang-reserved slots) and pins its response
bit-identical to the single-process kernel on the same inputs — run by
the full bench (``cli --config=cluster_bench``), skipped under
``--only gated`` (it pays a jax import + compile in a fresh process).

``python -m tosem_tpu.cli microbench --cluster`` runs it; ``--save`` /
``--check`` record/gate against ``results/bench_cluster.json`` floors
in ``ci.sh --perf``.
"""
from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

from tosem_tpu.serve.bench_common import (SuiteEmitter, closed_loop,
                                          paired_loop)
from tosem_tpu.utils.results import ResultRow

# Gated by ci.sh --perf: absolute throughput floors for both arms (min
# of rounds) plus the failover recovery ratio (phase-immune: pre and
# post rounds are adjacent in time). The cluster arm pays two RPC hops
# per request — its floor documents the cost of crossing process trees,
# it is NOT expected to beat the in-process data plane on a 2-CPU host.
GATED_CLUSTER_BENCHES = (
    "cluster_router_c16", "cluster_single_ref_c16",
    "cluster_failover_recovery",
    "cluster_decode_disagg_c16", "cluster_decode_coloc_c16",
    "cluster_decode_disagg_vs_coloc",
    "cluster_drain_migrate_vs_readmit",
    "router_hedged_p99",
)

# the hedged-tail A/B's bench ids (its own small cluster + emulated-
# network fault — kept out of the legacy router/failover block)
HEDGE_BENCH_IDS = ("router_hedged_p99", "router_unhedged_p99",
                   "router_hedge_tail_win")

# ``cli microbench --cluster --scenario=...`` subsets (mirrors the
# decode bench's SCENARIO_BENCHES shape)
CLUSTER_SCENARIOS = {
    "decode": ("cluster_decode_disagg_c16", "cluster_decode_coloc_c16",
               "cluster_decode_disagg_vs_coloc"),
    "migrate": ("cluster_drain_migrate_vs_readmit",
                "cluster_drain_errors"),
}

# ``cli microbench --control`` — the closed-loop diurnal/burst scenario
# (tosem_tpu/control/ acceptance leg), gated against
# results/bench_control.json in ci.sh --perf
GATED_CONTROL_BENCHES = (
    "control_steady_p99_ms", "control_steady_sheds",
    "control_burst_scaleup", "control_replica_convergence",
    "control_cold_serves",
)

DEFAULT_BASELINE = "results/bench_cluster.json"
DEFAULT_CONTROL_BASELINE = "results/bench_control.json"

BACKEND_REF = "tosem_tpu.serve.bench_serve:VectorWorkBackend"
BACKEND_KW = {"n": 256}

# cluster-decode workload: long prompts (a prefill costs several
# decode steps), page config sized so c16 plus admissions in flight
# never hit pressure. The disaggregation A/B runs MIXED traffic — 8
# decode-heavy "chat" clients + 8 prefill-only "embed" clients
# (max_new_tokens=1, the embedding/scoring class) — because that is
# the workload disaggregation exists for: on a colocated deployment
# every embed admit stalls the step loop and every embed occupies a
# step row doing nothing, starving the in-flight token streams, while
# the disaggregated arm resolves embeds ENTIRELY on the prefill tier.
# (Uniform all-chat traffic on this 2-CPU host is compute-conserving:
# XLA's intra-op threading already saturates both cores from one
# process, so no multi-process split beats one well-batched replica —
# measured, not assumed.)
DECODE_KW = dict(max_batch=16, max_len=512, page_size=16,
                 num_pages=768, max_new_tokens=32, dim=32, heads=2,
                 layers=2, mlp_dim=64)
DECODE_PROMPT_LEN = 480


def _fleet_with_errors(handle, n_clients: int, duration_s: float):
    """Closed-loop fleet that RECORDS failures instead of aborting —
    the failover window's client view. Returns (completed, errors)."""
    stop = time.perf_counter() + duration_s
    done = [0] * n_clients
    errors: List[BaseException] = []
    lock = threading.Lock()

    def client(i):
        while time.perf_counter() < stop:
            try:
                handle.call({"x": i}, timeout=120.0)
                done[i] += 1
            except BaseException as e:
                with lock:
                    errors.append(e)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return sum(done), errors


def run_cluster_benchmarks(trials: int = 3, min_s: float = 0.5,
                           quiet: bool = False,
                           only: Optional[set] = None) -> List[ResultRow]:
    """Interleaved A/B cluster benches; ``only`` restricts bench_ids.
    Router/failover/parity legs spawn the node-agent cluster; the
    cluster-decode legs (disaggregated prefill/decode A/B, drain-with-
    migration A/B) ride the actor-replica decode plane — each block
    only runs when a bench id it owns is wanted."""
    import tosem_tpu.runtime as rt

    em = SuiteEmitter("cluster", only)
    decode_ids = (set(CLUSTER_SCENARIOS["decode"])
                  | set(CLUSTER_SCENARIOS["migrate"]))
    hedge_ids = set(HEDGE_BENCH_IDS)
    legacy_wanted = only is None or bool(
        set(only) - decode_ids - hedge_ids)
    decode_wanted = only is None or bool(set(only) & decode_ids)
    hedge_wanted = only is None or bool(set(only) & hedge_ids)

    own_runtime = not rt.is_initialized()
    if own_runtime:
        rt.init(num_workers=2, memory_monitor=False)
    try:
        if legacy_wanted:
            _router_failover_benchmarks(em, trials, min_s, only)
        if hedge_wanted:
            _router_hedge_benchmarks(em, trials, min_s)
        if decode_wanted:
            _cluster_decode_benchmarks(em, trials, min_s)
            _cluster_drain_benchmarks(em, trials, min_s)
    finally:
        if own_runtime:
            rt.shutdown()
    return em.flush(quiet)


def _router_failover_benchmarks(em: SuiteEmitter, trials: int,
                                min_s: float,
                                only: Optional[set]) -> None:
    from tosem_tpu.cluster.node import RemoteNode
    from tosem_tpu.cluster.supervisor import NodePool
    from tosem_tpu.serve.bench_serve import VectorWorkBackend
    from tosem_tpu.serve.cluster_serve import ClusterServe
    from tosem_tpu.serve.core import Serve

    # single-process reference arm: the PR-5 serve data plane, same
    # backend, 2 in-process replica actors
    serve = Serve()
    serve.deploy("bench-ref", VectorWorkBackend, num_replicas=2,
                 max_retries=1, init_kwargs=dict(BACKEND_KW))
    h_ref = serve.get_handle("bench-ref")

    # cluster arm: 2 agents × capacity 4 (the survivor must be able to
    # re-host the victim's replicas), 4 replicas spread 2+2, 2 router
    # processes — every request crosses two process boundaries
    journal = os.path.join(tempfile.mkdtemp(prefix="bench_cluster_"),
                           "head.jsonl")
    pool = NodePool(journal_path=journal, miss_threshold=1,
                    probe_timeout=3.0)
    nodes = [RemoteNode.spawn_local(num_workers=8) for _ in range(2)]
    for i, n in enumerate(nodes):
        pool.add_node(n, name=f"n{i}")
    cs = ClusterServe(pool, num_routers=2, router_procs=True)
    try:
        dep = cs.deploy("bench-vec", BACKEND_REF, num_replicas=4,
                        strategy="spread", init_kwargs=dict(BACKEND_KW))
        h_cl = cs.get_handle("bench-vec")
        h_ref.call({"x": 0}, timeout=120.0)       # warm both arms
        h_cl.call({"x": 0})

        throughput_ids = {"cluster_router_c16", "cluster_single_ref_c16",
                          "cluster_vs_single"}
        if only is None or throughput_ids & only:
            cl16, ref16, ratios = [], [], []
            for _ in range(max(trials, 1)):
                # one A/B round: both arms see the same host phase
                a = closed_loop(h_cl.call, 16, min_s,
                                lambda i, k: {"x": i})
                b = closed_loop(h_ref.call, 16, min_s,
                                lambda i, k: {"x": i}, timeout=60.0)
                cl16.append(a)
                ref16.append(b)
                ratios.append(a / b if b else float("inf"))
            em.emit("cluster_router_c16",
                    "cluster serve 16 clients via router tier", cl16)
            em.emit("cluster_single_ref_c16",
                    "single-process serve 16 clients reference", ref16)
            em.emit("cluster_vs_single",
                    "cluster vs single-process throughput", ratios,
                    unit="x")

        # ---- failover: node kill under live traffic -------------------
        if em.want("cluster_failover_recovery"):
            # pre/post windows are seconds apart on a bimodal host, so
            # raw throughput is NOT comparable across the kill
            # (measured 6x phase swings). Recovery is therefore scored
            # against a CONTROL cluster deployment that shares the
            # victim arm's whole stack (same backend, replica count,
            # router tier) but is packed on the surviving node, with
            # both fleets run CONCURRENTLY over the same wall-clock
            # window (paired_loop) — a phase flip or GIL convoy hits
            # both arms in the same milliseconds. Even so, identical
            # deployments measure up to ~1.3x apart round to round on
            # this 2-CPU host (driver-GIL scheduling luck), so the
            # ratio-of-medians is asserted only against a CATASTROPHIC
            # bound (0.5x: a real failover bug — retry storms, lost
            # capacity, per-request timeouts — is a 5-100x drop), while
            # the deterministic acceptance criteria are hard: zero
            # client-surfaced errors, full re-placement. The recorded
            # row (capped at 1.0) lets the perf gate track recovery
            # release over release at the standard threshold.
            ctrl = cs.deploy("bench-control", BACKEND_REF,
                             num_replicas=4, strategy="pack",
                             init_kwargs=dict(BACKEND_KW))
            h_ctrl = cs.get_handle("bench-control")
            h_ctrl.call({"x": 0})
            ctrl_nodes = {r.node for r in ctrl.replicas}
            # the victim hosts failover-arm replicas but NO control
            # replicas (the control must ride through the kill intact)
            victim = next(r.node for r in dep.replicas
                          if r.node not in ctrl_nodes)

            def paired_ratio():
                a, b = paired_loop(h_cl.call, h_ctrl.call, 8, min_s,
                                   lambda i, k: {"x": i})
                return a, (a / b if b else float("inf"))

            import statistics
            pre = [paired_ratio() for _ in range(3)]
            pre_med = statistics.median(r for _, r in pre)
            live = pool.live_nodes()

            killer_done = threading.Event()

            def killer():
                # kill mid-window, then drive the detector so death is
                # DISCOVERED (probe path), not merely announced
                time.sleep(min_s / 2)
                live[victim].kill()
                while victim in pool.live_nodes():
                    pool.detector.check_once()
                killer_done.set()

            kt = threading.Thread(target=killer)
            kt.start()
            completed, errors = _fleet_with_errors(
                h_cl, 16, duration_s=max(3.0, 4 * min_s))
            kt.join()
            if not killer_done.is_set() or victim in pool.live_nodes():
                raise RuntimeError("victim node was never declared dead")
            if errors:
                raise RuntimeError(
                    f"{len(errors)} logical requests surfaced errors "
                    f"across the node kill (first: {errors[0]!r}) — "
                    "failover must lose nothing beyond transparent "
                    "retries")
            survivors = {r.node for r in dep.replicas}
            if victim in survivors or len(dep.replicas) != 4:
                raise RuntimeError(
                    f"replicas not re-placed off {victim}: "
                    f"{[(r.replica_id, r.node) for r in dep.replicas]}")
            post = [paired_ratio() for _ in range(3)]
            post_med = statistics.median(r for _, r in post)
            recovery = post_med / pre_med if pre_med else 0.0
            if recovery < 0.5:
                raise RuntimeError(
                    f"post-failover victim/control ratio "
                    f"{post_med:.2f} is {recovery:.2f}x of the "
                    f"pre-kill median {pre_med:.2f} — below even the "
                    "catastrophic 0.5x bound; failover is broken, not "
                    "noisy")
            # recorded capped at 1.0 ("fully recovered"): an above-1.0
            # raw ratio (noise favoring the post window) would bake an
            # unmeetable baseline into the perf gate. Enforcement is
            # split: the in-bench hard-fail above catches catastrophic
            # (<0.5x) breakage deterministically, while the >=0.8x
            # acceptance level is held by this gated row's baseline +
            # threshold across runs — a single run's ratio is too
            # noisy on this host to hard-assert 0.8 (identical
            # deployments measure up to ~1.3x apart)
            row = em.emit("cluster_failover_recovery",
                          "post-node-kill throughput vs pre-kill floor",
                          [min(recovery, 1.0)], unit="x")
            if row is not None:
                row.extra.update({
                    "raw_recovery": round(recovery, 2),
                    "pre_rounds": [[round(v, 1), round(r, 2)]
                                   for v, r in pre],
                    "post_rounds": [[round(v, 1), round(r, 2)]
                                    for v, r in post],
                    "killed_node": victim,
                    "requests_through_kill": completed,
                    "errors_through_kill": len(errors)})
            erow = em.emit("cluster_failover_errors",
                           "client-surfaced errors across node kill",
                           [float(len(errors))], unit="errors")
            if erow is not None:
                erow.extra["completed"] = completed
            cs.delete("bench-control")

        # ---- sharded parity (not gated: fresh-process jax import) -----
        if em.want("cluster_sharded_parity"):
            import numpy as np
            from tosem_tpu.serve.backends import ShardedAttentionBackend
            t0 = time.perf_counter()
            cs.deploy("bench-shard", ShardedAttentionBackend,
                      num_replicas=1, sharding=(1, 2),
                      init_kwargs={"batch": 2, "heads": 2, "seq": 128,
                                   "dim": 64},
                      warmup_shapes=[0])
            h_sh = cs.get_handle("bench-shard")
            out = h_sh.call({"seed": 7})
            ref = ShardedAttentionBackend.reference(
                {"seed": 7}, batch=2, heads=2, seq=128, dim=64)
            got = np.asarray(out["out"])
            if got.tobytes() != ref.tobytes():
                raise RuntimeError(
                    "sharded dp×tp response is not bit-identical to the "
                    f"single-process reference (max abs diff "
                    f"{np.abs(got - ref).max()})")
            row = em.record("cluster_sharded_parity",
                            "sharded replica bit-identity vs reference",
                            1.0, 0.0, unit="bool")
            row.extra.update({"mesh": out["mesh"],
                              "devices": out["devices"],
                              "deploy_s": round(time.perf_counter() - t0,
                                                1)})
            cs.delete("bench-shard")

        # ---- sharded PAGED DECODE parity (not gated: fresh-process
        # jax import) — the dp×tp decode kernel on a gang-reserved
        # replica must be bit-identical to the single-process lowering,
        # including the window/page_offsets/multi-token-q modes
        if em.want("cluster_paged_parity"):
            import numpy as np
            from tosem_tpu.serve.backends import ShardedPagedDecodeBackend
            t0 = time.perf_counter()
            dims = {"batch": 4, "heads": 4, "head_dim": 16, "pages": 16,
                    "page_size": 8, "table_w": 4}
            cs.deploy("bench-paged", ShardedPagedDecodeBackend,
                      num_replicas=1, sharding=(2, 2),
                      init_kwargs=dims, warmup_shapes=[0])
            h_pg = cs.get_handle("bench-paged")
            for req in ({"seed": 3}, {"seed": 4, "q_tokens": 3},
                        {"seed": 5, "q_tokens": 2, "offsets": True}):
                out = h_pg.call(dict(req))
                ref = ShardedPagedDecodeBackend.reference(req, **dims)
                got = np.asarray(out["out"])
                if got.tobytes() != ref.tobytes():
                    raise RuntimeError(
                        f"sharded paged decode response for {req} is "
                        "not bit-identical to the single-process "
                        f"lowering (max abs diff "
                        f"{np.abs(got - ref).max()})")
            row = em.record("cluster_paged_parity",
                            "sharded paged decode bit-identity "
                            "(incl. multi-q/offsets)", 1.0, 0.0,
                            unit="bool")
            row.extra.update({"mesh": out["mesh"],
                              "devices": out["devices"],
                              "deploy_s": round(time.perf_counter() - t0,
                                                1)})
            cs.delete("bench-paged")
    finally:
        cs.close()
        pool.close(close_nodes=True)
        serve.delete("bench-ref")


def _router_hedge_benchmarks(em: SuiteEmitter, trials: int,
                             min_s: float) -> None:
    """Hedged vs unhedged tail latency with one chaos-slowed replica,
    interleaved A/B.

    Two in-process routers share the SAME 2-replica deployment, table
    pushes, and host phase; the only difference is the hedge knob.
    The emulated network then turns one replica's node gray (100 ms
    injected dispatch latency — ~20x the healthy service time, the
    slow-but-alive fault crash-stop detection never sees). Per round,
    both arms run the same sequential request train: the unhedged arm's
    p99 IS the injected delay (half its picks land on the gray
    replica), while the hedged arm must cap its p99 at roughly the
    quantile-derived hedge delay plus one healthy service time. Hard
    asserts: zero errors on both arms, hedges actually fired, and the
    hedged p99 well under the injected delay; the gated
    ``router_hedged_p99`` row holds the level release over release."""
    from tosem_tpu.chaos import network as _net
    from tosem_tpu.cluster.node import RemoteNode
    from tosem_tpu.cluster.supervisor import NodePool
    from tosem_tpu.serve.cluster_serve import ClusterServe
    from tosem_tpu.serve.router import RouterCore, RouterPolicy

    if not any(em.want(b) for b in HEDGE_BENCH_IDS):
        return
    slow_s = 0.1
    pool = NodePool(miss_threshold=2, probe_timeout=3.0)
    cs = None
    try:
        for i in range(2):
            pool.add_node(RemoteNode.spawn_local(num_workers=2),
                          name=f"n{i}")
        cs = ClusterServe(
            pool, num_routers=1, router_procs=False,
            router_policy=RouterPolicy(hedge_after_s=0.02,
                                       hedge_quantile=0.9,
                                       hedge_min_samples=8))
        # the unhedged control rides the same table pushes: register it
        # before the deploy so every push reaches both routers
        unhedged = RouterCore(name="router-unhedged",
                              policy=RouterPolicy())
        with cs._lock:
            cs._routers.append(unhedged)
        cs.deploy("hedge-bench", BACKEND_REF, num_replicas=2,
                  strategy="spread", init_kwargs=dict(BACKEND_KW))
        hedged = next(r for r in cs._routers_snapshot()
                      if r is not unhedged)
        # warm clients AND the latency rings: the first calls pay
        # connection setup, and the hedge delay is a ring quantile —
        # enough healthy samples must bury the cold-start outliers
        # below the hedge quantile before the fault is armed
        for router in (hedged, unhedged):
            for i in range(32):
                router.route("hedge-bench", {"x": i})
        slow_node = cs.chaos_slow_replica_node("hedge-bench", slow_s)

        def arm_p99_ms(router, n=48):
            lat = []
            for i in range(n):
                t0 = time.perf_counter()
                router.route("hedge-bench", {"x": i})
                lat.append(time.perf_counter() - t0)
            lat.sort()
            return lat[min(int(len(lat) * 0.99), len(lat) - 1)] * 1e3

        hedged_p99, unhedged_p99, wins = [], [], []
        for _ in range(max(trials, 1)):
            # one A/B round: both arms adjacent in time
            a = arm_p99_ms(hedged)
            b = arm_p99_ms(unhedged)
            hedged_p99.append(a)
            unhedged_p99.append(b)
            wins.append(b / a if a else float("inf"))
        hst, ust = hedged.stats(), unhedged.stats()
        if hst["errors"] or ust["errors"]:
            raise RuntimeError(
                f"routed errors under the gray fault (hedged "
                f"{hst['errors']}, unhedged {ust['errors']}) — a slow "
                "node is not a dead node; nothing may fail")
        if hst["hedged"] < 1 or hst["hedge_wins"] < 1:
            raise RuntimeError(
                f"the hedged arm never hedged (fired {hst['hedged']}, "
                f"won {hst['hedge_wins']}) against a {slow_s * 1e3:.0f}"
                "ms-gray replica")
        if max(hedged_p99) >= slow_s * 1e3 * 0.8:
            raise RuntimeError(
                f"hedged p99 {max(hedged_p99):.0f}ms sits at the "
                f"injected {slow_s * 1e3:.0f}ms gray delay — hedging "
                "failed to cap the tail")
        row = em.emit("router_hedged_p99",
                      "hedged routed p99, one chaos-slowed replica",
                      hedged_p99, unit="ms", lower_is_better=True)
        if row is not None:
            row.extra.update({
                "slow_node": slow_node,
                "injected_delay_ms": slow_s * 1e3,
                "hedges_fired": hst["hedged"],
                "hedge_wins": hst["hedge_wins"]})
        em.emit("router_unhedged_p99",
                "unhedged routed p99, one chaos-slowed replica",
                unhedged_p99, unit="ms", lower_is_better=True)
        em.emit("router_hedge_tail_win",
                "unhedged vs hedged p99 under the gray fault",
                wins, unit="x")
    finally:
        if cs is not None:
            cs.close()
        pool.close(close_nodes=True)
        _net.state().reset()


# ---------------------------------------------------------------------------
# cluster-scale decode: disaggregated prefill/decode + drain-with-migration


def _decode_ids(i):
    return [(7 * i + j) % 96 + 1 for j in range(DECODE_PROMPT_LEN)]


def _decode_prompts(n):
    """Uniform decode-heavy prompts (the drain bench's fleet)."""
    return [{"ids": _decode_ids(i)} for i in range(n)]


def _mixed_request(i, k):
    """The disaggregation A/B's c16 mixed fleet: clients 0-7 are
    decode-heavy chat streams (staggered budgets de-synchronize
    turnover), clients 8-15 prefill-only embed/scoring traffic."""
    if i < 8:
        return {"ids": _decode_ids(i), "max_new_tokens": 16 + (i % 8)}
    return {"ids": _decode_ids(i), "max_new_tokens": 1}


def _cluster_decode_benchmarks(em: SuiteEmitter, trials: int,
                               min_s: float) -> None:
    """Disaggregated prefill/decode vs colocated, interleaved A/B on
    the MIXED c16 fleet (see :func:`_mixed_request`).

    Same backend config and page budget on both arms. The colocated
    arm runs the single-replica layout that measured FASTEST for it
    (one well-batched replica: XLA intra-op threading saturates the
    host; multi-replica colocated layouts measured 20-40% slower) —
    the baseline is colocated-at-its-best, not a strawman. The
    disaggregated arm splits the same two processes into a prefill
    replica and a decode replica: embeds resolve at admit on the
    prefill tier, chat pages stream worker→worker to the decode tier
    (live KV migration), so the step loop never stalls behind a
    prefill. Completed units = generated tokens across BOTH classes.
    Decode rounds are floored at 1.2s — a 0.4s CI window measures
    admission latency, not token throughput."""
    from tosem_tpu.serve.backends import BertDecodeBackend
    from tosem_tpu.serve.batching import DecodePolicy
    from tosem_tpu.serve.core import Serve

    ids = CLUSTER_SCENARIOS["decode"]
    if not any(em.want(b) for b in ids):
        return
    min_s = max(min_s, 1.2)
    serve = Serve()
    try:
        serve.deploy("bench-coloc", BertDecodeBackend,
                     init_kwargs=dict(DECODE_KW), num_replicas=1,
                     decode_policy=DecodePolicy(max_active=16),
                     max_retries=2,
                     warmup_shapes=[DECODE_PROMPT_LEN])
        serve.deploy("bench-disagg", BertDecodeBackend,
                     init_kwargs=dict(DECODE_KW), num_replicas=2,
                     decode_policy=DecodePolicy(max_active=16,
                                                prefill_replicas=1),
                     max_retries=2,
                     warmup_shapes=[DECODE_PROMPT_LEN])
        h_co = serve.get_handle("bench-coloc")
        h_di = serve.get_handle("bench-disagg")
        # warm both data paths end to end (first call pays tracing) and
        # pin the arms bit-identical on the same chat prompt
        a = h_di.call(_mixed_request(0, 0), timeout=300.0)
        b = h_co.call(_mixed_request(0, 0), timeout=300.0)
        if a["tokens"] != b["tokens"]:
            raise RuntimeError("disaggregated and colocated decode "
                               "disagree on the same prompt")
        h_di.call(_mixed_request(8, 0), timeout=300.0)
        h_co.call(_mixed_request(8, 0), timeout=300.0)
        di_rates, co_rates, ratios = [], [], []
        splits = {}
        for _ in range(max(trials, 1)):
            # one A/B round: both arms see the same host phase
            chat = [0.0, 0.0]

            def count(out, slot=0):
                n = float(len(out["generated"]))
                if n > 1:
                    chat[slot] += n
                return n
            di = closed_loop(h_di.call, 16, min_s, _mixed_request,
                             count_of=lambda o: count(o, 0),
                             timeout=300.0)
            co = closed_loop(h_co.call, 16, min_s, _mixed_request,
                             count_of=lambda o: count(o, 1),
                             timeout=300.0)
            di_rates.append(di)
            co_rates.append(co)
            ratios.append(di / co if co else float("inf"))
            splits = {"disagg_chat_tok_s": round(chat[0] / min_s, 1),
                      "coloc_chat_tok_s": round(chat[1] / min_s, 1)}
        st = serve.get_deployment("bench-disagg").stats()
        if st.get("kv_migrations", 0) < 1:
            raise RuntimeError(
                "disaggregated arm recorded zero migrations — the "
                "prefill tier never handed anything to the decode "
                f"tier (stats {st})")
        row = em.emit("cluster_decode_disagg_c16",
                      "disaggregated prefill/decode token throughput, "
                      "mixed c16", di_rates, unit="tok/s")
        if row is not None:
            row.extra.update({
                "kv_migrations": st.get("kv_migrations"),
                "prompt_len": DECODE_PROMPT_LEN,
                "fleet": "8 chat + 8 embed", **splits})
        em.emit("cluster_decode_coloc_c16",
                "colocated prefill+decode token throughput, "
                "mixed c16", co_rates, unit="tok/s")
        em.emit("cluster_decode_disagg_vs_coloc",
                "disaggregated vs colocated token throughput",
                ratios, unit="x")
    finally:
        for name in ("bench-coloc", "bench-disagg"):
            try:
                serve.delete(name)
            except Exception:
                pass


class ControlLoadBackend:
    """Fixed-service-time backend with warm/cold accounting — the
    control-plane bench's unit of work. ``warmup()`` simulates the AOT
    executable build (``compile_s``); a call served BEFORE warmup
    counts a ``cold_serve`` and pays the build inline — exactly the
    tail latency the warm-before-traffic contract must make impossible.
    ``stats()`` rides the replica's stats RPC so the bench can assert
    zero cold serves across every replica autoscaling ever placed."""

    def __init__(self, delay_s: float = 0.02, compile_s: float = 0.25):
        self._delay_s = float(delay_s)
        self._compile_s = float(compile_s)
        self._warmed = False
        self._cold_serves = 0
        self._lock = threading.Lock()

    def warmup(self, shapes):
        time.sleep(self._compile_s)
        with self._lock:
            self._warmed = True
        return {"warmed": len(shapes)}

    def call(self, request):
        with self._lock:
            cold = not self._warmed
            if cold:
                self._cold_serves += 1
                self._warmed = True        # the JIT memoizes either way
        if cold:
            time.sleep(self._compile_s)
        time.sleep(self._delay_s)
        return {"x": request.get("x", 0)}

    def stats(self):
        with self._lock:
            return {"cold_serves": self._cold_serves,
                    "warmed": self._warmed}


def _open_loop(call, rate_hz: float, duration_s: float,
               start_index: int = 0, workers: int = 48):
    """Open-loop load: requests fire on the offered-rate schedule
    whether or not earlier ones completed (closed-loop fleets
    self-throttle under overload — useless for proving admission).
    Latency is measured from the request's SCHEDULED time, so client-
    side queueing counts against the system like it does for a user.
    Returns (samples, errors): samples are ``(sched_offset_s,
    latency_s, outcome)`` with outcome ``ok`` | ``shed``."""
    import queue

    from tosem_tpu.control.admission import Overloaded

    q: "queue.Queue" = queue.Queue()
    samples: List[tuple] = []
    errors: List[BaseException] = []
    lock = threading.Lock()
    start = time.perf_counter() + 0.05

    def worker():
        while True:
            item = q.get()
            if item is None:
                return
            sched, i = item
            klass = "decode" if i % 2 else "bulk"
            try:
                call({"x": i}, klass=klass)
                out = "ok"
            except Overloaded:
                out = "shed"
            except BaseException as e:  # pragma: no cover - asserted 0
                out = "error"
                with lock:
                    errors.append(e)
            dt = time.perf_counter() - sched
            with lock:
                samples.append((sched - start, dt, out))

    threads = [threading.Thread(target=worker) for _ in range(workers)]
    for t in threads:
        t.start()
    n = int(rate_hz * duration_s)
    for i in range(n):
        sched = start + i / rate_hz
        now = time.perf_counter()
        if sched > now:
            time.sleep(sched - now)
        q.put((sched, start_index + i))
    for _ in threads:
        q.put(None)
    for t in threads:
        t.join()
    return samples, errors


def _p99(samples: List[tuple], after_s: float = 0.0) -> float:
    lat = sorted(dt for off, dt, out in samples
                 if out == "ok" and off >= after_s)
    if not lat:
        return float("nan")
    return lat[min(int(len(lat) * 0.99), len(lat) - 1)]


def _sheds(samples: List[tuple], after_s: float = 0.0) -> int:
    return sum(1 for off, _, out in samples
               if out == "shed" and off >= after_s)


def run_control_benchmarks(trials: int = 1, min_s: float = 0.5,
                           quiet: bool = False,
                           only: Optional[set] = None) -> List[ResultRow]:
    """The control-plane acceptance scenario: an open-loop diurnal
    1×→8×→1× ramp over a 2-node cluster with the FULL closed loop live
    — :class:`~tosem_tpu.control.plane.ControlPlane` scaling the
    deployment's replicas AND the router tier from the queue-depth
    rollup, SLO admission with decode/bulk priority classes, and
    affinity-scored placement over a model ledger.

    Deterministic acceptance criteria are hard asserts; the gated rows
    track them release over release:

    - zero UNTYPED client errors anywhere (sheds are typed);
    - zero sheds at steady state (burst-shoulder sheds allowed);
    - steady-state p99 under the deployment's latency budget;
    - the burst scales replicas up (>= 2) and both replica count and
      router-tier count RETURN TO BASELINE within the scale-down
      window;
    - zero cold-compile serves on every replica ever placed (scale-up
      warms before the routing table sees the replica)."""
    import statistics as _stats

    from tosem_tpu.cluster.node import RemoteNode
    from tosem_tpu.cluster.rpc import RpcClient
    from tosem_tpu.cluster.supervisor import NodePool
    from tosem_tpu.control import (ControlPlane, ModelLedger,
                                   PlacementScorer, ScalePolicy)
    from tosem_tpu.control.admission import SLOConfig
    from tosem_tpu.serve.cluster_serve import ClusterServe

    em = SuiteEmitter("control", only)
    if not any(em.want(b) for b in GATED_CONTROL_BENCHES):
        return em.flush(quiet)

    slo = SLOConfig(latency_budget_s=0.5, est_service_s=0.02,
                    target_inflight_per_replica=8,
                    classes={"decode": 10, "bulk": 0}, aging_s=0.2)
    r0 = 24.0                      # steady offered load, req/s
    burst = 8 * r0                 # the 8x diurnal peak
    steady_s, burst_s, settle_s = 2.0, 2.5, 6.0

    pool = NodePool(miss_threshold=2, probe_timeout=3.0)
    cs = None
    plane = None
    try:
        for i in range(2):
            pool.add_node(RemoteNode.spawn_local(num_workers=4),
                          name=f"n{i}")
        cs = ClusterServe(
            pool, num_routers=1, router_procs=False,
            placement_scorer=PlacementScorer(ModelLedger(
                budget_per_node=4.0)))
        dep = cs.deploy(
            "diurnal", "tosem_tpu.serve.bench_cluster:ControlLoadBackend",
            num_replicas=1, strategy="pack",
            init_kwargs={"delay_s": 0.02, "compile_s": 0.25},
            warmup_shapes=[1], slo=slo)
        plane = ControlPlane(
            cs,
            deployments={"diurnal": ScalePolicy(
                min_units=1, max_units=4, target_per_unit=1.0,
                idle_ticks_before_downscale=3, max_up_per_tick=2)},
            router_policy=ScalePolicy(
                min_units=1, max_units=2, target_per_unit=4.0,
                idle_ticks_before_downscale=3, max_up_per_tick=1))
        h = cs.get_handle("diurnal")
        h.call({"x": 0}, klass="decode")      # end-to-end warm
        plane.run(interval=0.1)

        p99s, rounds_extra = [], {}
        scaleups, cold_totals = [], []
        shed_rounds, conv_rounds = [], []
        for _round in range(max(trials, 1)):
            cold_by_rid: Dict[str, int] = {}

            def harvest_cold():
                with cs._lock:
                    reps = list(dep.replicas)
                for r in reps:
                    try:
                        with RpcClient(r.address) as cli:
                            st = cli.call("stats")
                        cold_by_rid[r.replica_id] = int(
                            st.get("cold_serves", 0))
                    except Exception:
                        pass

            a_samples, a_err = _open_loop(h.call, r0, steady_s)
            max_reps = [len(dep.replicas)]
            max_routers = [cs.num_routers()]

            def watch():
                while not watch_stop.is_set():
                    max_reps[0] = max(max_reps[0], len(dep.replicas))
                    max_routers[0] = max(max_routers[0],
                                         cs.num_routers())
                    watch_stop.wait(0.05)

            watch_stop = threading.Event()
            wt = threading.Thread(target=watch)
            wt.start()
            b_samples, b_err = _open_loop(h.call, burst, burst_s,
                                          start_index=10_000)
            harvest_cold()         # replicas the burst placed, pre-drain
            c_samples, c_err = _open_loop(h.call, r0, settle_s,
                                          start_index=50_000)
            watch_stop.set()
            wt.join()
            harvest_cold()
            errors = a_err + b_err + c_err
            if errors:
                raise RuntimeError(
                    f"{len(errors)} UNTYPED client errors across the "
                    f"diurnal ramp (first: {errors[0]!r}) — overload "
                    "must shed typed, never fail raw")
            # steady state = phase A after warm shoulder + the tail of
            # phase C (after the scale-down window)
            steady_sheds = (_sheds(a_samples, after_s=0.3)
                            + _sheds(c_samples, after_s=settle_s / 2))
            if steady_sheds:
                raise RuntimeError(
                    f"{steady_sheds} requests shed at STEADY state — "
                    "admission must only shed into the burst shoulder")
            p99 = max(_p99(a_samples, after_s=0.3),
                      _p99(c_samples, after_s=settle_s / 2))
            if not p99 < slo.latency_budget_s:
                raise RuntimeError(
                    f"steady-state p99 {p99 * 1e3:.0f}ms breaches the "
                    f"{slo.latency_budget_s * 1e3:.0f}ms budget")
            if max_reps[0] < 2:
                raise RuntimeError(
                    f"the 8x burst never scaled up (max replicas "
                    f"{max_reps[0]}) — the loop is open, not closed")
            # convergence: both axes back at baseline
            deadline = time.perf_counter() + 6.0
            while time.perf_counter() < deadline and (
                    len(dep.replicas) > 1 or cs.num_routers() > 1):
                time.sleep(0.1)
            converged = (len(dep.replicas) == 1
                         and cs.num_routers() == 1)
            if not converged:
                raise RuntimeError(
                    f"no post-burst convergence: replicas="
                    f"{len(dep.replicas)} routers={cs.num_routers()} "
                    "(baseline is 1/1)")
            cold = sum(cold_by_rid.values())
            if cold:
                raise RuntimeError(
                    f"{cold} cold-compile serves ({cold_by_rid}) — "
                    "scale-up must warm BEFORE the routing table sees "
                    "a replica")
            p99s.append(p99 * 1e3)
            scaleups.append(float(max_reps[0]))
            cold_totals.append(float(cold))
            # measured values (provably 0 / 1.0 past the hard asserts
            # above — recorded as measurements, not constants, so the
            # rows' provenance stays honest)
            shed_rounds.append(float(steady_sheds))
            conv_rounds.append(float(converged))
            rounds_extra = {
                "burst_sheds": _sheds(b_samples) + _sheds(
                    c_samples, after_s=0.0) - _sheds(
                    c_samples, after_s=settle_s / 2),
                "max_routers": max_routers[0],
                "steady_rate_hz": r0, "burst_rate_hz": burst,
                "steady_p50_ms": round(_stats.median(
                    dt for _, dt, out in a_samples
                    if out == "ok") * 1e3, 2),
                "cold_by_replica": cold_by_rid,
                "scale_history": [
                    d for d in list(plane.history)[-40:]
                    if d.get("replicas") != d.get("new_replicas")],
            }
        row = em.emit("control_steady_p99_ms",
                      "diurnal scenario steady-state p99 latency",
                      p99s, unit="ms", lower_is_better=True)
        if row is not None:
            row.extra.update(rounds_extra)
        em.emit("control_steady_sheds",
                "typed sheds at steady state (must be zero)",
                shed_rounds, unit="errors")
        em.emit("control_burst_scaleup",
                "peak replica count reached during the 8x burst",
                scaleups, unit="replicas")
        em.emit("control_replica_convergence",
                "replica+router counts returned to baseline post-burst",
                conv_rounds, unit="bool")
        em.emit("control_cold_serves",
                "cold-compile serves across every replica placed",
                cold_totals, unit="errors")
    finally:
        if plane is not None:
            plane.stop()
        if cs is not None:
            cs.close()
        pool.close(close_nodes=True)
    return em.flush(quiet)


def _cluster_drain_benchmarks(em: SuiteEmitter, trials: int,
                              min_s: float) -> None:
    """Drain-with-migration vs step-0 re-admission, interleaved A/B.

    Per round: admit 8 long sequences on a 2-replica deployment, let
    every active sequence pass ~2/3 of its budget, drain the loaded
    replica (arm A: live migration — remaining tokens only; arm B: the
    PR-8 re-admission — re-prefill plus EVERY token again), and time
    completion from the drain. The ratio is tokens-to-catch-up made
    wall-clock; the migrate arm additionally hard-asserts zero errors,
    zero step-0 restarts, and >= 1 migration."""
    import time as _time

    from tosem_tpu.serve.backends import BertDecodeBackend
    from tosem_tpu.serve.batching import DecodePolicy
    from tosem_tpu.serve.core import Serve

    ids = CLUSTER_SCENARIOS["migrate"]
    if not any(em.want(b) for b in ids):
        return
    kw = dict(DECODE_KW)
    kw["max_new_tokens"] = 32
    prompts = _decode_prompts(8)
    # drain deep into the decode: the re-admission arm recomputes the
    # prefill plus EVERYTHING generated so far, the migration arm pays
    # a few ms of page transfer plus only the remaining steps
    drain_at = 13 * kw["max_new_tokens"] // 16

    serve = Serve()
    try:
        serve.deploy("bench-drain", BertDecodeBackend,
                     init_kwargs=kw, num_replicas=2,
                     decode_policy=DecodePolicy(max_active=8),
                     max_retries=4,
                     warmup_shapes=[DECODE_PROMPT_LEN])
        dep = serve.get_deployment("bench-drain")
        h = serve.get_handle("bench-drain")
        h.call(dict(prompts[0]), timeout=300.0)      # warm end to end
        q = dep._queue

        def drain_round(migrate):
            base = dep.stats()
            futs = [h.remote(dict(p)) for p in prompts]
            deadline = _time.time() + 120.0
            while _time.time() < deadline:
                with q._lock:
                    steps = [it.step for it in q._active]
                if steps and len(steps) + len(q._pending) >= len(
                        prompts) and min(steps) >= drain_at \
                        and not q._pending:
                    break
                _time.sleep(0.005)
            loads = q.replica_loads()
            with dep._lock:
                reps = list(dep._replicas)
            victim = max(reps, key=lambda r: loads.get(id(r), 0))
            tokens_at_drain = dep.stats()["tokens_emitted"]
            t0 = _time.perf_counter()
            res = q.drain_replica(victim, migrate=migrate)
            outs = [f.result(timeout=300.0) for f in futs]
            dt = _time.perf_counter() - t0
            st = dep.stats()
            catchup = st["tokens_emitted"] - tokens_at_drain
            errs = st["sequences_err"] - base["sequences_err"]
            if errs:
                raise RuntimeError(
                    f"{errs} sequences surfaced errors across the "
                    f"drain (migrate={migrate})")
            short = [o for o in outs
                     if len(o["generated"]) != kw["max_new_tokens"]]
            if short:
                raise RuntimeError(
                    f"{len(short)} sequences completed short of the "
                    "token budget — the drain lost work")
            if migrate:
                if res["migrated"] < 1:
                    raise RuntimeError(
                        f"drain migrated nothing ({res}) — the bench "
                        "drained an idle replica")
                step0 = (st["seqs_readmitted_step0"]
                         - base["seqs_readmitted_step0"])
                if step0:
                    raise RuntimeError(
                        f"{step0} sequences restarted from step 0 "
                        "under drain-with-migration")
            return dt, catchup, res

        ratios = []
        last = {}
        for _ in range(max(trials, 1)):
            # one A/B round, adjacent in time: migrate then re-admit.
            # The gated metric is TOKENS-TO-CATCH-UP (tokens the fleet
            # must generate after the drain to finish): deterministic
            # up to drain timing, where wall-clock ratios swing 2x+
            # because the migrate arm finishes in fractions of a
            # second on this host
            dt_m, cu_m, res_m = drain_round(migrate=True)
            dt_r, cu_r, res_r = drain_round(migrate=False)
            ratios.append(cu_r / cu_m if cu_m else float("inf"))
            last = {"migrate_s": round(dt_m, 3),
                    "readmit_s": round(dt_r, 3),
                    "migrate_catchup_tokens": cu_m,
                    "readmit_catchup_tokens": cu_r,
                    "wall_ratio": round(dt_r / dt_m, 2) if dt_m else 0,
                    "drain_migrate": res_m, "drain_readmit": res_r}
        row = em.emit("cluster_drain_migrate_vs_readmit",
                      "drain recovery: migration vs step-0 "
                      "re-admission (tokens-to-catch-up ratio)", ratios,
                      unit="x")
        if row is not None:
            row.extra.update(last)
            row.extra["drain_at_step"] = drain_at
        erow = em.record("cluster_drain_errors",
                         "client-surfaced errors across drains", 0.0,
                         0.0, unit="errors")
        erow.extra["rounds"] = len(ratios)
    finally:
        try:
            serve.delete("bench-drain")
        except Exception:
            pass
