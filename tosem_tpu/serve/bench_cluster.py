"""Cluster serving microbenchmarks — the multi-process closed-loop leg.

The first bench in the repo whose workload spans process trees: 2 node
agents × 2 replicas each behind the router tier
(:mod:`tosem_tpu.serve.cluster_serve`), interleaved A/B against the
single-process serve data plane on the SAME backend (per the
bench-noise protocol: both arms of a round share the host phase; the
absolute floors are min-of-rounds).

The acceptance leg is **failover**: a 16-client closed-loop fleet runs
THROUGH a mid-run node kill — the failure detector declares the node
dead, the controller re-places its replicas on the survivor under the
same ids, and routers re-admit in-flight requests from step 0. The
deterministic criteria are hard asserts: ZERO client-surfaced errors
(no logical request lost beyond transparent retries) and full
re-placement off the dead node. Throughput recovery is scored against
a same-shape CONTROL cluster deployment measured concurrently (the
only phase control that works here — see the leg's comment for the
measurement history), hard-failed only below a catastrophic 0.5x
bound, and recorded as a gated row so the perf gate tracks recovery
(vs the 1.0 baseline, standard threshold) release over release.

A non-gated parity leg deploys a ``sharding=(1, 2)`` replica (dp×tp
mesh in its own process, gang-reserved slots) and pins its response
bit-identical to the single-process kernel on the same inputs — run by
the full bench (``cli --config=cluster_bench``), skipped under
``--only gated`` (it pays a jax import + compile in a fresh process).

``python -m tosem_tpu.cli microbench --cluster`` runs it; ``--save`` /
``--check`` record/gate against ``results/bench_cluster.json`` floors
in ``ci.sh --perf``.
"""
from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import List, Optional

from tosem_tpu.serve.bench_common import (SuiteEmitter, closed_loop,
                                          paired_loop)
from tosem_tpu.utils.results import ResultRow

# Gated by ci.sh --perf: absolute throughput floors for both arms (min
# of rounds) plus the failover recovery ratio (phase-immune: pre and
# post rounds are adjacent in time). The cluster arm pays two RPC hops
# per request — its floor documents the cost of crossing process trees,
# it is NOT expected to beat the in-process data plane on a 2-CPU host.
GATED_CLUSTER_BENCHES = (
    "cluster_router_c16", "cluster_single_ref_c16",
    "cluster_failover_recovery",
)

DEFAULT_BASELINE = "results/bench_cluster.json"

BACKEND_REF = "tosem_tpu.serve.bench_serve:VectorWorkBackend"
BACKEND_KW = {"n": 256}


def _fleet_with_errors(handle, n_clients: int, duration_s: float):
    """Closed-loop fleet that RECORDS failures instead of aborting —
    the failover window's client view. Returns (completed, errors)."""
    stop = time.perf_counter() + duration_s
    done = [0] * n_clients
    errors: List[BaseException] = []
    lock = threading.Lock()

    def client(i):
        while time.perf_counter() < stop:
            try:
                handle.call({"x": i}, timeout=120.0)
                done[i] += 1
            except BaseException as e:
                with lock:
                    errors.append(e)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return sum(done), errors


def run_cluster_benchmarks(trials: int = 3, min_s: float = 0.5,
                           quiet: bool = False,
                           only: Optional[set] = None) -> List[ResultRow]:
    """Interleaved A/B cluster benches; ``only`` restricts bench_ids."""
    import tosem_tpu.runtime as rt
    from tosem_tpu.cluster.node import RemoteNode
    from tosem_tpu.cluster.supervisor import NodePool
    from tosem_tpu.serve.bench_serve import VectorWorkBackend
    from tosem_tpu.serve.cluster_serve import ClusterServe
    from tosem_tpu.serve.core import Serve

    em = SuiteEmitter("cluster", only)

    own_runtime = not rt.is_initialized()
    if own_runtime:
        rt.init(num_workers=2, memory_monitor=False)

    # single-process reference arm: the PR-5 serve data plane, same
    # backend, 2 in-process replica actors
    serve = Serve()
    serve.deploy("bench-ref", VectorWorkBackend, num_replicas=2,
                 max_retries=1, init_kwargs=dict(BACKEND_KW))
    h_ref = serve.get_handle("bench-ref")

    # cluster arm: 2 agents × capacity 4 (the survivor must be able to
    # re-host the victim's replicas), 4 replicas spread 2+2, 2 router
    # processes — every request crosses two process boundaries
    journal = os.path.join(tempfile.mkdtemp(prefix="bench_cluster_"),
                           "head.jsonl")
    pool = NodePool(journal_path=journal, miss_threshold=1,
                    probe_timeout=3.0)
    nodes = [RemoteNode.spawn_local(num_workers=8) for _ in range(2)]
    for i, n in enumerate(nodes):
        pool.add_node(n, name=f"n{i}")
    cs = ClusterServe(pool, num_routers=2, router_procs=True)
    try:
        dep = cs.deploy("bench-vec", BACKEND_REF, num_replicas=4,
                        strategy="spread", init_kwargs=dict(BACKEND_KW))
        h_cl = cs.get_handle("bench-vec")
        h_ref.call({"x": 0}, timeout=120.0)       # warm both arms
        h_cl.call({"x": 0})

        throughput_ids = {"cluster_router_c16", "cluster_single_ref_c16",
                          "cluster_vs_single"}
        if only is None or throughput_ids & only:
            cl16, ref16, ratios = [], [], []
            for _ in range(max(trials, 1)):
                # one A/B round: both arms see the same host phase
                a = closed_loop(h_cl.call, 16, min_s,
                                lambda i, k: {"x": i})
                b = closed_loop(h_ref.call, 16, min_s,
                                lambda i, k: {"x": i}, timeout=60.0)
                cl16.append(a)
                ref16.append(b)
                ratios.append(a / b if b else float("inf"))
            em.emit("cluster_router_c16",
                    "cluster serve 16 clients via router tier", cl16)
            em.emit("cluster_single_ref_c16",
                    "single-process serve 16 clients reference", ref16)
            em.emit("cluster_vs_single",
                    "cluster vs single-process throughput", ratios,
                    unit="x")

        # ---- failover: node kill under live traffic -------------------
        if em.want("cluster_failover_recovery"):
            # pre/post windows are seconds apart on a bimodal host, so
            # raw throughput is NOT comparable across the kill
            # (measured 6x phase swings). Recovery is therefore scored
            # against a CONTROL cluster deployment that shares the
            # victim arm's whole stack (same backend, replica count,
            # router tier) but is packed on the surviving node, with
            # both fleets run CONCURRENTLY over the same wall-clock
            # window (paired_loop) — a phase flip or GIL convoy hits
            # both arms in the same milliseconds. Even so, identical
            # deployments measure up to ~1.3x apart round to round on
            # this 2-CPU host (driver-GIL scheduling luck), so the
            # ratio-of-medians is asserted only against a CATASTROPHIC
            # bound (0.5x: a real failover bug — retry storms, lost
            # capacity, per-request timeouts — is a 5-100x drop), while
            # the deterministic acceptance criteria are hard: zero
            # client-surfaced errors, full re-placement. The recorded
            # row (capped at 1.0) lets the perf gate track recovery
            # release over release at the standard threshold.
            ctrl = cs.deploy("bench-control", BACKEND_REF,
                             num_replicas=4, strategy="pack",
                             init_kwargs=dict(BACKEND_KW))
            h_ctrl = cs.get_handle("bench-control")
            h_ctrl.call({"x": 0})
            ctrl_nodes = {r.node for r in ctrl.replicas}
            # the victim hosts failover-arm replicas but NO control
            # replicas (the control must ride through the kill intact)
            victim = next(r.node for r in dep.replicas
                          if r.node not in ctrl_nodes)

            def paired_ratio():
                a, b = paired_loop(h_cl.call, h_ctrl.call, 8, min_s,
                                   lambda i, k: {"x": i})
                return a, (a / b if b else float("inf"))

            import statistics
            pre = [paired_ratio() for _ in range(3)]
            pre_med = statistics.median(r for _, r in pre)
            live = pool.live_nodes()

            killer_done = threading.Event()

            def killer():
                # kill mid-window, then drive the detector so death is
                # DISCOVERED (probe path), not merely announced
                time.sleep(min_s / 2)
                live[victim].kill()
                while victim in pool.live_nodes():
                    pool.detector.check_once()
                killer_done.set()

            kt = threading.Thread(target=killer)
            kt.start()
            completed, errors = _fleet_with_errors(
                h_cl, 16, duration_s=max(3.0, 4 * min_s))
            kt.join()
            if not killer_done.is_set() or victim in pool.live_nodes():
                raise RuntimeError("victim node was never declared dead")
            if errors:
                raise RuntimeError(
                    f"{len(errors)} logical requests surfaced errors "
                    f"across the node kill (first: {errors[0]!r}) — "
                    "failover must lose nothing beyond transparent "
                    "retries")
            survivors = {r.node for r in dep.replicas}
            if victim in survivors or len(dep.replicas) != 4:
                raise RuntimeError(
                    f"replicas not re-placed off {victim}: "
                    f"{[(r.replica_id, r.node) for r in dep.replicas]}")
            post = [paired_ratio() for _ in range(3)]
            post_med = statistics.median(r for _, r in post)
            recovery = post_med / pre_med if pre_med else 0.0
            if recovery < 0.5:
                raise RuntimeError(
                    f"post-failover victim/control ratio "
                    f"{post_med:.2f} is {recovery:.2f}x of the "
                    f"pre-kill median {pre_med:.2f} — below even the "
                    "catastrophic 0.5x bound; failover is broken, not "
                    "noisy")
            # recorded capped at 1.0 ("fully recovered"): an above-1.0
            # raw ratio (noise favoring the post window) would bake an
            # unmeetable baseline into the perf gate. Enforcement is
            # split: the in-bench hard-fail above catches catastrophic
            # (<0.5x) breakage deterministically, while the >=0.8x
            # acceptance level is held by this gated row's baseline +
            # threshold across runs — a single run's ratio is too
            # noisy on this host to hard-assert 0.8 (identical
            # deployments measure up to ~1.3x apart)
            row = em.emit("cluster_failover_recovery",
                          "post-node-kill throughput vs pre-kill floor",
                          [min(recovery, 1.0)], unit="x")
            if row is not None:
                row.extra.update({
                    "raw_recovery": round(recovery, 2),
                    "pre_rounds": [[round(v, 1), round(r, 2)]
                                   for v, r in pre],
                    "post_rounds": [[round(v, 1), round(r, 2)]
                                    for v, r in post],
                    "killed_node": victim,
                    "requests_through_kill": completed,
                    "errors_through_kill": len(errors)})
            erow = em.emit("cluster_failover_errors",
                           "client-surfaced errors across node kill",
                           [float(len(errors))], unit="errors")
            if erow is not None:
                erow.extra["completed"] = completed
            cs.delete("bench-control")

        # ---- sharded parity (not gated: fresh-process jax import) -----
        if em.want("cluster_sharded_parity"):
            import numpy as np
            from tosem_tpu.serve.backends import ShardedAttentionBackend
            t0 = time.perf_counter()
            cs.deploy("bench-shard", ShardedAttentionBackend,
                      num_replicas=1, sharding=(1, 2),
                      init_kwargs={"batch": 2, "heads": 2, "seq": 128,
                                   "dim": 64},
                      warmup_shapes=[0])
            h_sh = cs.get_handle("bench-shard")
            out = h_sh.call({"seed": 7})
            ref = ShardedAttentionBackend.reference(
                {"seed": 7}, batch=2, heads=2, seq=128, dim=64)
            got = np.asarray(out["out"])
            if got.tobytes() != ref.tobytes():
                raise RuntimeError(
                    "sharded dp×tp response is not bit-identical to the "
                    f"single-process reference (max abs diff "
                    f"{np.abs(got - ref).max()})")
            row = em.record("cluster_sharded_parity",
                            "sharded replica bit-identity vs reference",
                            1.0, 0.0, unit="bool")
            row.extra.update({"mesh": out["mesh"],
                              "devices": out["devices"],
                              "deploy_s": round(time.perf_counter() - t0,
                                                1)})
            cs.delete("bench-shard")
    finally:
        cs.close()
        pool.close(close_nodes=True)
        serve.delete("bench-ref")
        if own_runtime:
            rt.shutdown()
    return em.flush(quiet)
