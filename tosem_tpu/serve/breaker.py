"""Per-deployment circuit breaker (closed / open / half-open).

The standard pattern (Nygard's "Release It!", the Hystrix/Envoy
outlier-detection role): after ``failure_threshold`` consecutive
failures the breaker OPENS and rejects requests instantly with
:class:`CircuitOpen` — protecting callers from piling onto a deployment
that is down, and the deployment from a retry storm while it restarts
replicas. After ``cooldown_s`` one probe request is admitted
(HALF_OPEN); its success closes the breaker, its failure re-opens it
and restarts the cool-down.

The clock is injectable so breaker tests are instant and deterministic
(the same replayability contract as :mod:`tosem_tpu.chaos`).
"""
from __future__ import annotations

import threading
import time
from typing import Callable

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitOpen(RuntimeError):
    """Request rejected without dispatch: the deployment's breaker is
    open (too many consecutive failures; retry after the cool-down)."""


class CircuitBreaker:
    """Thread-safe three-state breaker.

    Contract: call :meth:`allow` before dispatch (raises
    :class:`CircuitOpen` when rejecting) and keep its return value —
    True means *this request is the half-open probe*. Then exactly one
    of :meth:`record_success` / :meth:`record_failure` per allowed
    request, passing ``probe=`` what allow() returned; a probe
    abandoned without a verdict calls :meth:`release_probe`. Probe
    ownership is per-request so a stale non-probe request finishing
    late can never free or fail a probe it doesn't own.
    """

    def __init__(self, failure_threshold: int = 5, cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """Admit or reject a request. Returns True when the admitted
        request is the half-open probe (the caller must echo that via
        ``probe=`` on its record call, or :meth:`release_probe`)."""
        with self._lock:
            if self._state == CLOSED:
                return False
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.cooldown_s:
                    # cool-down elapsed: admit ONE probe
                    self._state = HALF_OPEN
                    self._probe_in_flight = True
                    return True
                raise CircuitOpen(
                    f"circuit open ({self._consecutive_failures} consecutive "
                    f"failures); retry after the "
                    f"{self.cooldown_s}s cool-down")
            # HALF_OPEN: only the single probe may pass
            if self._probe_in_flight:
                raise CircuitOpen("circuit half-open: probe in flight")
            self._probe_in_flight = True
            return True

    def release_probe(self) -> None:
        """Give up a PROBE (allow() returned True) without a verdict —
        e.g. the caller's wait timed out while the request may still
        land later. The probe slot is freed and the breaker returns to
        OPEN with its original open timestamp, so the next allow() can
        admit a fresh probe immediately; without this, an abandoned
        probe would wedge the breaker in 'probe in flight' forever.
        Only the probe's owner may call this (non-probe requests have
        nothing to release)."""
        with self._lock:
            if not self._probe_in_flight:
                return
            self._probe_in_flight = False
            if self._state == HALF_OPEN:
                self._state = OPEN

    def record_success(self, probe: bool = False) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if probe:
                self._probe_in_flight = False
            # any success is live evidence the backend serves requests
            self._state = CLOSED

    def record_failure(self, probe: bool = False, count: int = 1) -> None:
        """``count``: how many LOGICAL requests this failure represents.
        A 16-request micro-batch lost to a replica crash is 16 trips of
        evidence, not one dispatch — the batching data plane passes the
        batch's logical size so the breaker's view of the backend stays
        request-accurate (one lock hold either way). ``probe`` still
        applies once: a batch can carry at most one probe slot."""
        if count < 1:
            raise ValueError("count must be >= 1")
        with self._lock:
            self._consecutive_failures += count
            if probe:
                self._probe_in_flight = False
            if probe and self._state == HALF_OPEN:
                # the probe's verdict decides the half-open outcome —
                # but only while the breaker is STILL half-open; if a
                # concurrent success already closed it, the backend is
                # demonstrably serving and one failure must clear the
                # threshold like any other
                self._state = OPEN
                self._opened_at = self._clock()
            elif (self._state == CLOSED
                  and self._consecutive_failures >= self.failure_threshold):
                self._state = OPEN
                self._opened_at = self._clock()
            # non-probe failures while OPEN/HALF_OPEN only add to the
            # count — a stale request must not restart the cool-down or
            # steal the in-flight probe's verdict
