"""Cluster serving plane: node-spanning deployments behind a router tier.

The reference serves traffic across a cluster — a controller places
backend replicas on raylets, router/proxy actors load-balance over
them, and the GCS re-homes replicas when a node dies. This module is
that composition for OUR substrate: :class:`ClusterServe` turns a
deployment into a node-spanning service over the PR-2 cluster plane.

Three layers:

- **Placement** — replicas spread (or packed) across
  :class:`~tosem_tpu.cluster.supervisor.NodePool` nodes using the
  per-node capacity the agents report (``replica_slots_free``), with
  every placement journaled through the pool's
  :class:`~tosem_tpu.cluster.supervisor.HeadJournal` so
  :meth:`ClusterServe.recover` can rebuild a crashed head's routing
  table. A deployment may declare ``sharding=(dp, tp)``: each logical
  replica then pins ``dp*tp`` virtual devices (the agent sets
  ``XLA_FLAGS`` pre-spawn) and runs
  :func:`~tosem_tpu.parallel.flash.sharded_flash_attention` under a
  dp×tp mesh, with the node slots withheld from the task plane via a
  :mod:`~tosem_tpu.cluster.gang` reservation.
- **Routing** — replicated, stateless
  :class:`~tosem_tpu.serve.router.RouterCore` processes in front
  (consistent-hash affinity with queue-depth-aware spillover); the
  controller pushes versioned routing tables, clients fail over across
  routers (:class:`ClusterHandle`).
- **Failover** — the pool's failure detector declares a node dead →
  this controller drops its replicas from the table (pushed
  immediately, so routers stop picking corpses), journals the
  removals, and re-places the replicas on surviving nodes under the
  same replica ids (the consistent-hash ring stays stable). Requests
  in flight on the dead node are re-admitted from step 0 by the
  routers — exact for the deterministic backends (greedy decode,
  padded-program encode), one breaker trip per logical request.

Chaos seam: ``serve.route`` fires per client request routed through a
:class:`ClusterHandle` (actions ``kill_router`` / ``kill_node`` /
``slow_node``), so the canned ``router-chaos`` plan can kill a router
mid-traffic and then a replica node, and ``slow-node-hedge`` can turn
one replica's node gray (alive but slow), deterministically.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import re
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from tosem_tpu.chaos import hooks as _chaos
from tosem_tpu.chaos import network as _net
from tosem_tpu.cluster.fencing import StaleEpochError
from tosem_tpu.cluster.gang import GangReservation, _plan, reserve_gang
from tosem_tpu.cluster.node import RemoteNode
from tosem_tpu.cluster.supervisor import NodePool
from tosem_tpu.control.admission import Overloaded, SLOConfig
from tosem_tpu.runtime.common import DeadlineExceeded
from tosem_tpu.serve.breaker import CircuitOpen
from tosem_tpu.serve.router import (NoReplicaAvailable, RemoteRouter,
                                    ReplicaAppError, RouterCore,
                                    RouterPolicy)


class PlacementError(RuntimeError):
    """The requested replica layout does not fit the live nodes'
    reported capacity."""


class ClusterReplica:
    """One placed replica: id, host node, direct RPC address, and (for
    sharded replicas) the gang reservation withholding its slots."""

    def __init__(self, replica_id: str, deployment: str, node: str,
                 address: str, devices: int = 0,
                 gang: Optional[GangReservation] = None):
        self.replica_id = replica_id
        self.deployment = deployment
        self.node = node
        self.address = address
        self.devices = devices
        self.gang = gang

    def info(self) -> Dict[str, Any]:
        return {"replica_id": self.replica_id, "node": self.node,
                "address": self.address, "devices": self.devices}


class ClusterDeployment:
    """Spec + live placements of one node-spanning deployment."""

    def __init__(self, name: str, backend_ref: str,
                 init_kwargs: Dict[str, Any], num_replicas: int,
                 strategy: str, sharding: Optional[Tuple[int, int]],
                 warmup_shapes: Optional[Sequence] = None,
                 slo: Optional[SLOConfig] = None):
        self.name = name
        self.backend_ref = backend_ref
        self.init_kwargs = dict(init_kwargs)
        self.num_replicas = num_replicas
        self.strategy = strategy
        self.sharding = tuple(sharding) if sharding else None
        self.warmup_shapes = list(warmup_shapes or [])
        self.slo = slo
        self.replicas: List[ClusterReplica] = []

    @property
    def devices_per_replica(self) -> int:
        return (self.sharding[0] * self.sharding[1]
                if self.sharding else 0)

    def spec(self) -> Dict[str, Any]:
        """Journal-serializable deployment spec (what recover replays)."""
        return {"deployment": self.name, "backend_ref": self.backend_ref,
                "init_kwargs": json.dumps(self.init_kwargs,
                                          sort_keys=True),
                "num_replicas": self.num_replicas,
                "strategy": self.strategy,
                "sharding": list(self.sharding) if self.sharding else None,
                "warmup_shapes": self.warmup_shapes,
                "slo": self.slo.to_dict() if self.slo else None}

    @classmethod
    def from_spec(cls, spec: Dict[str, Any]) -> "ClusterDeployment":
        return cls(spec["deployment"], spec["backend_ref"],
                   json.loads(spec.get("init_kwargs") or "{}"),
                   int(spec["num_replicas"]), spec.get("strategy", "spread"),
                   tuple(spec["sharding"]) if spec.get("sharding") else None,
                   spec.get("warmup_shapes") or [],
                   slo=(SLOConfig.from_dict(spec["slo"])
                        if spec.get("slo") else None))


def plan_replicas(capacities: Dict[str, int], num_replicas: int,
                  strategy: str = "spread") -> Dict[str, int]:
    """Node → replica-count layout over reported free capacity.

    Rides the gang scheduler's planner (same spread/pack vocabulary —
    one placement algebra for bundles and replicas). Raises
    :class:`PlacementError` when the layout cannot fit right now."""
    if strategy not in ("spread", "pack"):
        raise ValueError(f"unknown placement strategy {strategy!r}; "
                         "choose 'spread' or 'pack'")
    usable = {n: c for n, c in capacities.items() if c > 0}
    plan = _plan(usable, num_replicas, strategy) if usable else None
    if plan is None:
        raise PlacementError(
            f"cannot place {num_replicas} replicas ({strategy}) on "
            f"capacities {capacities}")
    return plan


class ClusterHandle:
    """Client handle: routes through the router tier with failover.

    ``key`` pins a request to its consistent-hash replica (session /
    KV / compile-cache affinity); keyless requests go least-loaded.
    Router loss fails over to the next router transparently — the
    logical request is only surfaced as failed when NO router answers
    or the routed call itself fails typed (application error, open
    breaker, no replicas)."""

    def __init__(self, cs: "ClusterServe", name: str):
        self._cs = cs
        self._name = name
        self._rr = itertools.count()

    def call(self, request: Any, timeout: Optional[float] = None,
             key: Optional[str] = None,
             klass: Optional[str] = None) -> Any:
        """Route one request. ``timeout`` is the request's END-TO-END
        deadline budget: the router sheds it typed
        (:class:`~tosem_tpu.runtime.common.DeadlineExceeded`) the
        moment the budget cannot be met — before admission, and at
        every retry-loop top — instead of burning replica capacity on
        an answer the client has already abandoned. ``klass`` names
        the priority class for SLO-admitted deployments (decode
        classes preempt bulk in the router queue)."""
        self._cs._fire_route_chaos(self._name)
        routers = self._cs._routers_snapshot()
        if not routers:
            raise ConnectionError("no routers configured")
        start = next(self._rr)
        last: Optional[BaseException] = None
        for k in range(len(routers)):
            router = routers[(start + k) % len(routers)]
            try:
                return router.route(self._name, request, key=key,
                                    klass=klass, timeout_s=timeout)
            except (NoReplicaAvailable, ReplicaAppError, CircuitOpen,
                    Overloaded, DeadlineExceeded):
                raise               # typed verdicts: not a router death
            except (ConnectionError, TimeoutError, OSError) as e:
                last = e            # router gone: fail over to the next
                continue
            except Exception as e:
                raise self._translate(e) from None
        raise ConnectionError(
            f"no live router for deployment {self._name!r}"
            + (f" (last error: {last!r})" if last else ""))

    @staticmethod
    def _translate(e: Exception) -> BaseException:
        """Re-type a remote router error (the RPC layer ships
        ``repr(exc)``; prefix-match like RemoteNode._translate)."""
        msg = str(e)
        if msg.startswith("Overloaded("):
            # recover the retry hint the admission check computed — a
            # typed shed without its backoff number is half a verdict.
            # [retry_after=…] is the structural field _shed embeds for
            # exactly this parse; the prose fallback covers Overloaded
            # raised elsewhere
            m = (re.search(r"\[retry_after=(\d+(?:\.\d+)?)s\]", msg)
                 or re.search(r"estimated wait (\d+(?:\.\d+)?)s", msg))
            return Overloaded(
                msg, retry_after=float(m.group(1)) if m else 0.0)
        for prefix, typ in (("NoReplicaAvailable(", NoReplicaAvailable),
                            ("ReplicaAppError(", ReplicaAppError),
                            ("CircuitOpen(", CircuitOpen),
                            ("DeadlineExceeded(", DeadlineExceeded),
                            ("StaleEpochError(", StaleEpochError)):
            if msg.startswith(prefix):
                return typ(msg)
        return e


class ClusterServe:
    """The cluster serving controller (single-controller, like Serve —
    but its replicas are processes on OTHER nodes and its data plane is
    the replicated router tier, so the controller is off the request
    path entirely)."""

    def __init__(self, pool: NodePool, num_routers: int = 1,
                 router_procs: bool = True,
                 router_policy: Optional[RouterPolicy] = None,
                 replica_startup_timeout: float = 120.0,
                 placement_scorer: Optional[Any] = None):
        self.pool = pool
        self._lock = threading.RLock()
        self._deployments: Dict[str, ClusterDeployment] = {}
        self._version = 0
        self._rid_next: Dict[str, int] = {}
        self._replica_startup_timeout = replica_startup_timeout
        self._closed = False
        # multi-model multiplexing: single-replica placements (scale-up,
        # failover re-placement) score nodes by compile-cache / KV
        # affinity through this scorer and its model ledger; None keeps
        # the pre-control-plane best-capacity choice
        self._scorer = placement_scorer
        self._router_procs = router_procs
        self._router_policy = router_policy
        self._router_seq = max(1, num_routers)
        # telemetry state (guarded by self._lock in stats(): /-/stats
        # is served by a threaded HTTP server, so scrapes race)
        self._metrics: Optional[Dict[str, Any]] = None
        self._exported_placed: set = set()
        self._exported_nodes: set = set()
        self._mirrored: Dict[Tuple[str, str, str], int] = {}
        self._routers: List[Union[RemoteRouter, RouterCore]] = []
        for i in range(max(1, num_routers)):
            if router_procs:
                self._routers.append(RemoteRouter.spawn_local(
                    name=f"router{i}", policy=router_policy))
            else:
                self._routers.append(
                    RouterCore(name=f"router{i}", policy=router_policy))
        pool.add_death_listener(self._on_node_dead)
        # gray-failure wiring: SUSPECT nodes (detector phi-accrual /
        # missed-probe state) are flagged in the pushed table so routers
        # de-prefer — not drop — their replicas before death is declared
        self._suspect_nodes: set = set()
        add_suspect = getattr(pool, "add_suspect_listener", None)
        if add_suspect is not None:
            add_suspect(self._on_node_suspect)

    @property
    def epoch(self) -> int:
        """The head's fencing epoch (the pool journal's lease term);
        stamped on placements and KV adoptions so a superseded head's
        writes are rejected typed by every receiver."""
        return int(getattr(self.pool, "epoch", 0) or 0)

    # -- capacity / placement ------------------------------------------

    def _capacities(self, per_replica: int = 1,
                    exclude: Sequence[str] = ()) -> Dict[str, int]:
        """Free replica slots per live node, in units of ONE replica
        (a dp×tp replica consumes ``per_replica`` agent slots)."""
        caps: Dict[str, int] = {}
        for name, node in self.pool.live_nodes().items():
            if name in exclude:
                continue
            try:
                st = node.stats()
            except Exception:
                continue            # unprobeable now: not a candidate
            free = int(st.get("replica_slots_free",
                              st.get("free_slots", 0)))
            caps[name] = free // max(1, per_replica)
        return caps

    def _next_rid(self, name: str) -> str:
        n = self._rid_next.get(name, 0)
        self._rid_next[name] = n + 1
        return f"{name}#r{n}"

    def _start_replica(self, dep: ClusterDeployment, node_name: str,
                       node: RemoteNode, replica_id: str
                       ) -> ClusterReplica:
        """Place one replica on ``node``: gang-reserve its device slots
        (sharded), spawn the worker, journal the placement."""
        devices = dep.devices_per_replica
        gang: Optional[GangReservation] = None
        init_kwargs = dict(dep.init_kwargs)
        if dep.sharding:
            dp, tp = dep.sharding
            init_kwargs.setdefault("dp", dp)
            init_kwargs.setdefault("tp", tp)
            # withhold the replica's cores from the task plane for its
            # whole lifetime — all-or-nothing on this node, no waiting
            # (the planner already checked capacity; a race just fails
            # this node and the caller picks another)
            gang = reserve_gang([node], devices, strategy="strict_pack",
                                timeout=0.0)
        try:
            address = node.start_replica(
                replica_id, dep.backend_ref, init_kwargs,
                devices=devices,
                startup_timeout=self._replica_startup_timeout,
                epoch=self.epoch)
        except BaseException:
            if gang is not None:
                gang.release()
            raise
        rep = ClusterReplica(replica_id, dep.name, node_name, address,
                             devices=devices, gang=gang)
        self.pool.record_event(
            "replica_placed", deployment=dep.name, replica_id=replica_id,
            node=node_name, address=address, devices=devices,
            gang_id=gang.pg_id if gang else None)
        return rep

    def _warm_replica(self, dep: ClusterDeployment,
                      rep: ClusterReplica) -> None:
        if dep.warmup_shapes:
            from tosem_tpu.cluster.rpc import RpcClient
            with RpcClient(rep.address) as cli:
                cli.call("warmup", list(dep.warmup_shapes))
        if self._scorer is not None:
            # the model's executable is now resident on this node: LRU-
            # ledger it (cold models may be evicted to fit) and PIN it
            # for this replica — eviction must skip models with traffic
            ledger = self._scorer.ledger
            evicted = ledger.record_warm(rep.node, dep.name)
            ledger.pin(rep.node, dep.name, rep.replica_id)
            if evicted:
                from tosem_tpu.obs.metrics import control_plane_metrics
                control_plane_metrics()["model_evictions"].inc(
                    float(len(evicted)))

    def _unpin_replica(self, dep: ClusterDeployment,
                       rep: ClusterReplica) -> None:
        if self._scorer is not None:
            self._scorer.ledger.unpin(rep.node, dep.name,
                                      rep.replica_id)

    def _discard_replica(self, dep: ClusterDeployment,
                         rep: ClusterReplica, node: Optional[RemoteNode],
                         reason: str) -> None:
        """Stop and release a started-but-unwanted replica (warm
        failure, delete race) — the ONE place start-side resources
        (process, gang, ledger pin) are unwound."""
        self._unpin_replica(dep, rep)
        if node is not None:
            try:
                node.stop_replica(rep.replica_id,
                                      epoch=self.epoch)
            except Exception:
                pass
        if rep.gang is not None:
            rep.gang.release()
        self.pool.record_event("replica_removed", deployment=dep.name,
                               replica_id=rep.replica_id, reason=reason)

    def _finish_placement(self, dep: ClusterDeployment,
                          rep: ClusterReplica,
                          node: Optional[RemoteNode]) -> bool:
        """Warm, then register, one just-started replica (shared by
        scale-up and failover re-placement — the delete-races-placement
        handshake must not exist twice). A warm failure or a delete
        race DISCARDS the replica instead of leaking its process/gang:
        placement is contained per replica, so a repeating failure
        cannot bleed node slots tick over tick. True = the replica
        entered ``dep.replicas``."""
        try:
            self._warm_replica(dep, rep)
        except Exception as e:
            self.pool.record_event("replica_lost", deployment=dep.name,
                                   replica_id=rep.replica_id,
                                   error=repr(e))
            self._discard_replica(dep, rep, node, reason="warmup failed")
            return False
        with self._lock:
            # a delete/failed-deploy can race this placement: if the
            # deployment is no longer registered, the fresh replica
            # must be torn down, not leaked as an orphan the journal
            # records placed after deployment_deleted
            if self._deployments.get(dep.name) is not dep:
                registered = False
            else:
                dep.replicas.append(rep)
                registered = True
        if not registered:
            self._discard_replica(dep, rep, node,
                                  reason="deployment gone")
            return False
        return True

    # -- control plane -------------------------------------------------

    def deploy(self, name: str, backend: Any, *, num_replicas: int = 2,
               strategy: str = "spread",
               sharding: Optional[Tuple[int, int]] = None,
               init_kwargs: Optional[Dict[str, Any]] = None,
               warmup_shapes: Optional[Sequence] = None,
               slo: Optional[SLOConfig] = None
               ) -> ClusterDeployment:
        """Place ``num_replicas`` of ``backend`` (a class or a
        ``"module:qualname"`` ref importable on the nodes) across the
        pool and route traffic to them. ``sharding=(dp, tp)`` makes
        each logical replica a dp×tp-meshed sharded program (the
        backend receives ``dp``/``tp`` kwargs). ``slo`` turns on
        SLO-aware admission at every router: overload rejects typed
        (:class:`~tosem_tpu.control.admission.Overloaded`) under the
        declared latency budget, with priority classes."""
        ref = (backend if isinstance(backend, str)
               else f"{backend.__module__}:{backend.__qualname__}")
        dep = ClusterDeployment(name, ref, init_kwargs or {},
                                num_replicas, strategy, sharding,
                                warmup_shapes, slo=slo)
        with self._lock:
            if self._closed:
                raise RuntimeError("controller is closed")
            if name in self._deployments:
                raise ValueError(f"deployment {name!r} already exists")
            self._deployments[name] = dep
        self.pool.record_event("deployment_created", **dep.spec())
        try:
            caps = self._capacities(
                per_replica=max(1, dep.devices_per_replica))
            counts = plan_replicas(caps, num_replicas, strategy)
            nodes = self.pool.live_nodes()
            for node_name in sorted(counts):
                for _ in range(counts[node_name]):
                    rep = self._start_replica(
                        dep, node_name, nodes[node_name],
                        self._next_rid(name))
                    with self._lock:
                        dep.replicas.append(rep)
            with self._lock:
                to_warm = list(dep.replicas)
            for rep in to_warm:
                self._warm_replica(dep, rep)
        except BaseException:
            # unregister FIRST: the deployment is already visible to
            # the node-death listener, and a failover re-placement
            # racing this teardown must find the deployment gone
            # rather than re-place into a dying one
            with self._lock:
                self._deployments.pop(name, None)
            self._teardown_deployment(dep)
            self.pool.record_event("deployment_deleted", deployment=name,
                                   reason="deploy failed")
            raise
        self._push_table()
        return dep

    def get_handle(self, name: str) -> ClusterHandle:
        with self._lock:
            if name not in self._deployments:
                raise KeyError(f"no deployment {name!r}")
        return ClusterHandle(self, name)

    def get_deployment(self, name: str) -> Optional[ClusterDeployment]:
        with self._lock:
            return self._deployments.get(name)

    def list_deployments(self) -> List[str]:
        with self._lock:
            return sorted(self._deployments)

    def delete(self, name: str) -> None:
        with self._lock:
            dep = self._deployments.pop(name, None)
        if dep is None:
            return
        self._teardown_deployment(dep)
        self.pool.record_event("deployment_deleted", deployment=name)
        self._push_table()

    # -- autoscaling (the ControlPlane's actuator) ---------------------

    def scale(self, name: str, num_replicas: int) -> Dict[str, Any]:
        """Move deployment ``name`` to ``num_replicas`` (the control
        plane's actuator).

        Scale-UP places each new replica and **warms its compile cache
        before it enters the routing table** — the router tier only
        sees the replica after ``warmup_shapes`` compiled, so its first
        request never pays a JIT. A node dying mid-placement (the
        ``scale-under-kill`` chaos window) is contained per replica:
        the warming replica never joins ``dep.replicas`` — it cannot
        be counted as capacity or routed to — and placement retries on
        surviving nodes.

        Scale-DOWN removes the least-loaded replicas from routing
        FIRST (typed ``NodeDrainingError``-style fail-fast: no fresh
        traffic lands on a leaving replica), live-migrates their
        in-flight decode sequences to survivors (PR 11's KV migration
        — zero step-0 restarts), then stops the processes."""
        if num_replicas < 1:
            raise ValueError("a deployment needs at least one replica; "
                             "use ClusterServe.delete to tear it down")
        with self._lock:
            if self._closed:
                raise RuntimeError("controller is closed")
            dep = self._deployments.get(name)
            if dep is None:
                raise KeyError(f"no deployment {name!r}")
            current = len(dep.replicas)
        out = {"deployment": name, "from": current, "to": num_replicas,
               "placed": 0, "removed": 0, "sequences_migrated": 0}
        if num_replicas > current:
            out["placed"] = self._scale_up(dep, num_replicas - current)
        elif num_replicas < current:
            removed, migrated = self._scale_down(
                dep, current - num_replicas)
            out["removed"], out["sequences_migrated"] = removed, migrated
        with self._lock:
            dep.num_replicas = len(dep.replicas)
        self.pool.record_event("deployment_scaled", deployment=name,
                               **{k: v for k, v in out.items()
                                  if k != "deployment"})
        return out

    def _scale_up(self, dep: ClusterDeployment, count: int) -> int:
        placed = 0
        for _ in range(count):
            rep = None
            exclude: List[str] = []
            for _attempt in range(3):
                caps = self._capacities(
                    per_replica=max(1, dep.devices_per_replica),
                    exclude=exclude)
                try:
                    node_name = self._pick_node(dep, caps)
                except PlacementError:
                    break
                self._fire_scale_chaos(dep.name, node_name)
                node = self.pool.live_nodes().get(node_name)
                if node is None:
                    # the chosen node died between pick and placement:
                    # nothing was started there, nothing to count —
                    # retry on the survivors
                    exclude.append(node_name)
                    continue
                rid = self._next_rid(dep.name)
                try:
                    rep = self._start_replica(dep, node_name, node, rid)
                except Exception as e:
                    # mid-placement node death: the half-started
                    # replica is NOT appended to dep.replicas, so the
                    # control loop's capacity view and the routing
                    # table never include it
                    self.pool.record_event(
                        "replica_lost", deployment=dep.name,
                        replica_id=rid, error=repr(e))
                    exclude.append(node_name)
                    continue
                break
            if rep is None:
                break               # no capacity now: next tick retries
            # warm BEFORE routing: the replica enters the table (and
            # takes traffic) only with its compile cache filled
            if not self._finish_placement(dep, rep, node):
                break               # discarded (warm failure / delete)
            placed += 1
        if placed:
            self._push_table()
        return placed

    def _scale_down(self, dep: ClusterDeployment,
                    count: int) -> Tuple[int, int]:
        from tosem_tpu.cluster.rpc import RpcClient
        with self._lock:
            reps = list(dep.replicas)
        count = min(count, len(reps) - 1)   # never below one replica
        if count <= 0:
            return 0, 0
        loads: Dict[str, int] = {}
        for r in reps:
            try:
                with RpcClient(r.address) as cli:
                    loads[r.replica_id] = int(cli.call("load"))
            except Exception:
                # unprobeable replica: most attractive victim (likely
                # already dead)
                loads[r.replica_id] = -1
        victims = sorted(reps, key=lambda r: (loads[r.replica_id],
                                              r.replica_id))[:count]
        with self._lock:
            for v in victims:
                if v in dep.replicas:
                    dep.replicas.remove(v)
        # stop NEW traffic first (the drain-before-stop contract), then
        # move live decode state, then stop the processes
        self._push_table()
        migrated = 0
        live = self.pool.live_nodes()
        for v in victims:
            with self._lock:
                survivors = list(dep.replicas)
            migrated += self._migrate_replica_seqs(dep, v, survivors)
            self._unpin_replica(dep, v)
            node = live.get(v.node)
            if node is not None:
                try:
                    node.stop_replica(v.replica_id,
                                      epoch=self.epoch)
                except Exception:
                    pass
            if v.gang is not None:
                v.gang.release()
            self.pool.record_event(
                "replica_removed", deployment=dep.name,
                replica_id=v.replica_id, reason="scale_down",
                node=v.node)
        return len(victims), migrated

    def scale_routers(self, num_routers: int) -> int:
        """Grow/shrink the router TIER (the second closed-loop axis):
        fresh routers receive the current table+admission push before
        any client can reach them; shrink closes the tail routers —
        clients holding their addresses fail over, by design."""
        if num_routers < 1:
            raise ValueError("the router tier needs at least one router")
        with self._lock:
            if self._closed:
                return len(self._routers)
            cur = len(self._routers)
        if num_routers > cur:
            fresh: List[Union[RemoteRouter, RouterCore]] = []
            for _ in range(num_routers - cur):
                with self._lock:
                    name = f"router{self._router_seq}"
                    self._router_seq += 1
                if self._router_procs:
                    fresh.append(RemoteRouter.spawn_local(
                        name=name, policy=self._router_policy))
                else:
                    fresh.append(RouterCore(
                        name=name, policy=self._router_policy))
            with self._lock:
                self._routers.extend(fresh)
            self._push_table()      # the fresh routers catch up here
            self.pool.record_event("routers_scaled", count=num_routers,
                                   direction="up")
        elif num_routers < cur:
            with self._lock:
                victims = self._routers[num_routers:]
                self._routers = self._routers[:num_routers]
            for router in victims:
                try:
                    router.close()
                except Exception:
                    pass
            # re-push so survivors learn the NEW shard count: a stale
            # _shards leaves each survivor admitting 1/old_count of the
            # SLO budget — permanent under-admission
            self._push_table()
            self.pool.record_event("routers_scaled", count=num_routers,
                                   direction="down")
        with self._lock:
            return len(self._routers)

    def num_routers(self) -> int:
        with self._lock:
            return len(self._routers)

    def _fire_scale_chaos(self, deployment: str, node_name: str) -> None:
        """Chaos seam ``control.scale``: fired once per scale-up
        placement with the chosen target node — ``kill_node`` SIGKILLs
        that node and declares it dead BEFORE the replica starts (the
        mid-scale-up death window the ``scale-under-kill`` plan
        pins)."""
        act = _chaos.fire("control.scale", target=deployment)
        if act is None:
            return
        if act["action"] == "kill_node":
            node = self.pool.live_nodes().get(node_name)
            if node is not None:
                node.kill()
                self.pool.detector.declare_dead(node_name)

    def _teardown_deployment(self, dep: ClusterDeployment) -> None:
        nodes = self.pool.live_nodes()
        with self._lock:
            reps, dep.replicas = list(dep.replicas), []
        for rep in reps:
            node = nodes.get(rep.node)
            if node is not None:
                try:
                    node.stop_replica(rep.replica_id,
                                      epoch=self.epoch)
                except Exception:
                    pass            # dead node: its replicas died too
            if rep.gang is not None:
                rep.gang.release()
            self._unpin_replica(dep, rep)
            self.pool.record_event("replica_removed", deployment=dep.name,
                                   replica_id=rep.replica_id,
                                   reason="deleted")

    # -- routing table -------------------------------------------------

    def _routers_snapshot(self) -> List[Union[RemoteRouter, RouterCore]]:
        with self._lock:
            return list(self._routers)

    def _push_table(self) -> int:
        """Push the current placements to every router (versioned, so a
        racing push over another connection can never roll a router
        back). Unreachable routers are skipped — they are either dead
        (clients fail over) or will catch up on the next push."""
        with self._lock:
            self._version += 1
            version = self._version
            suspect = set(self._suspect_nodes)
            table = {name: [dict(rep.info(),
                                 suspect=(rep.node in suspect))
                            for rep in dep.replicas]
                     for name, dep in self._deployments.items()}
            routers = list(self._routers)
            # each router admits 1/N of the deployment's budget: the
            # SLO is an AGGREGATE contract, and scaling the router
            # tier must not multiply the admitted inflight
            admission = {
                name: {**dep.slo.to_dict(),
                       "_shards": max(1, len(routers))}
                for name, dep in self._deployments.items()
                if dep.slo is not None}
        for router in routers:
            try:
                router.update_table(table, version, admission)
            except Exception:
                pass
        return version

    def table_version(self) -> int:
        with self._lock:
            return self._version

    # -- failover ------------------------------------------------------

    def _on_node_suspect(self, node_name: str, node: RemoteNode,
                         entering: bool) -> None:
        """Pool suspect listener (the detector's pre-death state): flag
        the node's replicas in the routing table so routers de-prefer
        them — traffic drains toward healthy replicas BEFORE the death
        verdict, instead of piling retries onto a gray node — and clear
        the flag when a probe succeeds again."""
        with self._lock:
            if entering:
                self._suspect_nodes.add(node_name)
            else:
                self._suspect_nodes.discard(node_name)
            if self._metrics is None:
                from tosem_tpu.obs.metrics import cluster_serve_metrics
                self._metrics = cluster_serve_metrics()
            if entering:
                self._metrics["suspect_nodes"].set(1.0, (node_name,))
            else:
                self._metrics["suspect_nodes"].remove((node_name,))
        self._push_table()

    def _on_node_dead(self, node_name: str, node: RemoteNode) -> None:
        """Pool death listener: drop the node's replicas from routing
        (pushed immediately), then re-place them on survivors under
        the SAME replica ids — the hash ring stays stable, so affinity
        keys land on the re-placed replica, not a shuffled one."""
        with self._lock:
            # a dead node's suspect flag (and its gauge row) dies with it
            self._suspect_nodes.discard(node_name)
            if self._metrics is not None:
                self._metrics["suspect_nodes"].remove((node_name,))
            lost: List[Tuple[ClusterDeployment, ClusterReplica]] = []
            for dep in self._deployments.values():
                mine = [r for r in dep.replicas if r.node == node_name]
                for rep in mine:
                    dep.replicas.remove(rep)
                    lost.append((dep, rep))
        if self._scorer is not None:
            # the node's ledger (residency AND pins) dies with it —
            # never zero it, REMOVE it
            self._scorer.ledger.drop_node(node_name)
        if not lost:
            return
        self._push_table()
        for dep, rep in lost:
            self.pool.record_event(
                "replica_removed", deployment=dep.name,
                replica_id=rep.replica_id, reason="node_death",
                node=node_name)
            # the gang died with its node; release() is a no-op on a
            # dead agent but clears the driver-side handle
            if rep.gang is not None:
                rep.gang.release()
            try:
                self._place_one(dep, rep.replica_id,
                                exclude=(node_name,))
            except Exception as e:
                self.pool.record_event(
                    "replica_lost", deployment=dep.name,
                    replica_id=rep.replica_id, error=repr(e))
        self._push_table()

    def _pick_node(self, dep: ClusterDeployment,
                   caps: Dict[str, int]) -> str:
        """Node choice for ONE replica: affinity-scored when a
        placement scorer is configured (warm compile cache /
        co-residency / pressure — see
        :class:`~tosem_tpu.control.multiplex.PlacementScorer`),
        best-free-capacity otherwise (the pre-control-plane rule)."""
        candidates = [n for n, c in caps.items() if c > 0]
        if not candidates:
            raise PlacementError(
                f"no capacity for a replica of {dep.name!r} "
                f"(capacities {caps})")
        if self._scorer is not None:
            with self._lock:
                co: Dict[str, int] = {}
                for r in dep.replicas:
                    co[r.node] = co.get(r.node, 0) + 1
            pick = self._scorer.pick(
                {n: caps[n] for n in candidates}, dep.name, co)
            if pick is not None:
                return pick
        return max(sorted(candidates), key=lambda n: caps[n])

    def _place_one(self, dep: ClusterDeployment, replica_id: str,
                   exclude: Sequence[str] = ()) -> ClusterReplica:
        """Re-place one replica on the best surviving node."""
        caps = self._capacities(
            per_replica=max(1, dep.devices_per_replica), exclude=exclude)
        node_name = self._pick_node(dep, caps)
        node = self.pool.live_nodes()[node_name]
        rep = self._start_replica(dep, node_name, node, replica_id)
        if not self._finish_placement(dep, rep, node):
            raise PlacementError(
                f"replica {replica_id} was discarded during placement "
                "(deployment deleted, or warmup failed)")
        return rep

    # -- node drain (live KV migration) --------------------------------

    def _migrate_replica_seqs(self, dep: ClusterDeployment,
                              rep: ClusterReplica,
                              survivors: Sequence[ClusterReplica]
                              ) -> int:
        """Live-migrate ``rep``'s in-flight decode sequences onto
        ``survivors`` (backends exposing the migration surface —
        ``list_seqs``/``transport_address``/``send_seq``/``adopt_seq``;
        page bytes stream node→node over
        :mod:`tosem_tpu.cluster.transport`, the driver only brokers
        addresses). Shared by :meth:`drain_node` and replica-level
        scale-down — a scaled-away decode replica must not restart its
        sequences at step 0 any more than a drained node's. Returns the
        migrated-sequence count; failures fall back to the re-admission
        path per sequence."""
        from tosem_tpu.cluster.rpc import RpcClient, RpcError
        if not survivors:
            return 0
        migrated = 0
        try:
            with contextlib.ExitStack() as stack:
                src_cli = stack.enter_context(RpcClient(rep.address))
                seqs = src_cli.call("backend_call", "list_seqs")
                if not seqs:
                    return 0
                # one client + transport address per survivor;
                # sequences round-robin over them so one replica does
                # not absorb every migrated page
                dsts = []
                for r in survivors:
                    try:
                        cli = stack.enter_context(RpcClient(r.address))
                        dsts.append((cli, cli.call(
                            "backend_call", "transport_address")))
                    except (RpcError, ConnectionError,
                            TimeoutError, OSError):
                        continue
                if not dsts:
                    return 0
                for j, sid in enumerate(seqs):
                    dst_cli, addr = dsts[j % len(dsts)]
                    # per-sequence containment: one failed migration
                    # (pressure on the destination, a torn stream)
                    # must not abandon the REST of the replica's
                    # sequences to step-0 recompute
                    try:
                        src_cli.call("backend_call", "send_seq", sid,
                                     addr)
                        dst_cli.call("backend_call", "adopt_seq", sid,
                                     _epoch=self.epoch)
                        src_cli.call("backend_call", "release", sid)
                        migrated += 1
                    except (RpcError, ConnectionError,
                            TimeoutError, OSError):
                        continue
        except (RpcError, ConnectionError, TimeoutError, OSError):
            pass  # backend without the surface / replica gone:
            #       sequences fall back to the re-admission path
        return migrated

    def drain_node(self, node_name: str) -> Dict[str, Any]:
        """Gracefully drain ``node_name``: for every replica placed
        there, live-migrate its in-flight decode sequences to a
        SURVIVOR replica of the same deployment (backends exposing the
        migration surface — ``list_seqs``/``transport_address``/
        ``send_seq``/``adopt_seq``; page bytes stream node→node over
        :mod:`tosem_tpu.cluster.transport`, the driver only brokers
        addresses), drop the node from routing, re-place its replicas
        on surviving capacity under the same ids, and stop its
        processes. Unlike node DEATH (step-0 re-admission), a drained
        node's sequences continue from their current step. Returns
        ``{"replicas_moved", "sequences_migrated", "deployments"}``;
        journaled as ``node_drained``."""
        with self._lock:
            doomed: List[Tuple[ClusterDeployment, ClusterReplica]] = []
            for dep in self._deployments.values():
                for rep in [r for r in dep.replicas
                            if r.node == node_name]:
                    dep.replicas.remove(rep)
                    doomed.append((dep, rep))
        if not doomed:
            return {"replicas_moved": 0, "sequences_migrated": 0,
                    "deployments": []}
        # stop NEW traffic to the draining replicas first: routers must
        # not admit fresh sequences onto state that is about to move
        self._push_table()
        migrated = 0
        for dep, rep in doomed:
            with self._lock:
                survivors = [r for r in dep.replicas
                             if r.node != node_name]
            migrated += self._migrate_replica_seqs(dep, rep, survivors)
        nodes = self.pool.live_nodes()
        node = nodes.get(node_name)
        for dep, rep in doomed:
            self.pool.record_event(
                "replica_removed", deployment=dep.name,
                replica_id=rep.replica_id, reason="node_drain",
                node=node_name)
            self._unpin_replica(dep, rep)
            if node is not None:
                try:
                    node.stop_replica(rep.replica_id,
                                      epoch=self.epoch)
                except Exception:
                    pass
            if rep.gang is not None:
                rep.gang.release()
            try:
                self._place_one(dep, rep.replica_id,
                                exclude=(node_name,))
            except Exception as e:
                self.pool.record_event(
                    "replica_lost", deployment=dep.name,
                    replica_id=rep.replica_id, error=repr(e))
        self.pool.record_event("node_drained", node=node_name,
                               replicas=len(doomed),
                               sequences_migrated=migrated)
        self._push_table()
        return {"replicas_moved": len(doomed),
                "sequences_migrated": migrated,
                "deployments": sorted({d.name for d, _ in doomed})}

    # -- chaos seam ----------------------------------------------------

    def _fire_route_chaos(self, deployment: str) -> None:
        act = _chaos.fire("serve.route", target=deployment)
        if act is None:
            return
        if act["action"] == "kill_router":
            self.chaos_kill_router()
        elif act["action"] == "kill_node":
            self.chaos_kill_replica_node(deployment)
        elif act["action"] == "slow_node":
            self.chaos_slow_replica_node(
                deployment, float(act.get("delay_s") or 0.0))

    def chaos_slow_replica_node(self, deployment: str,
                                delay_s: float) -> Optional[str]:
        """Arm a gray fault: the node hosting ``deployment``'s LAST
        replica answers every dispatch ``delay_s`` late (the emulated-
        network state routers consult) — the node is alive and correct,
        just slow. Hedged routing is what keeps the tail flat through
        this; the ``slow-node-hedge`` plan pins exactly that."""
        with self._lock:
            dep = self._deployments.get(deployment)
            if dep is None or not dep.replicas:
                return None
            node_name = dep.replicas[-1].node
        _net.state().slow_node(node_name, delay_s)
        return node_name

    def chaos_kill_router(self) -> Optional[str]:
        """SIGKILL the first live router process (chaos: the client's
        next attempt on it fails and must fail over)."""
        for router in self._routers_snapshot():
            if isinstance(router, RemoteRouter) and \
                    router._proc is not None and \
                    router._proc.poll() is None:
                router.kill()
                return router.name
        return None

    def chaos_kill_replica_node(self, deployment: str) -> Optional[str]:
        """SIGKILL the first live node hosting a replica of
        ``deployment`` and declare it dead out-of-band (the detector's
        declare_dead path) — failover runs synchronously, the caller's
        request then rides the refreshed table."""
        with self._lock:
            dep = self._deployments.get(deployment)
            hosts = [r.node for r in dep.replicas] if dep else []
        live = self.pool.live_nodes()
        for node_name in hosts:
            node = live.get(node_name)
            if node is not None:
                node.kill()
                self.pool.detector.declare_dead(node_name)
                return node_name
        return None

    # -- head crash-restart --------------------------------------------

    @classmethod
    def recover(cls, journal_path: str, num_routers: int = 1,
                router_procs: bool = True, probe_timeout: float = 2.0,
                router_policy: Optional[RouterPolicy] = None,
                **pool_kwargs: Any) -> "ClusterServe":
        """Rebuild a crashed head's serving plane from its journal:
        recover the node pool, re-adopt replica processes that
        OUTLIVED the head (a head crash is not a node crash — the
        agents and their replicas keep serving), re-place the ones
        that did not, and push a fresh routing table."""
        pool = NodePool.recover(journal_path, probe_timeout=probe_timeout,
                                **pool_kwargs)
        cs = cls(pool, num_routers=num_routers, router_procs=router_procs,
                 router_policy=router_policy)
        specs: Dict[str, Dict[str, Any]] = getattr(pool, "deployments", {})
        placements: Dict[str, Dict[str, Any]] = getattr(
            pool, "placements", {})
        with cs._lock:
            for name, spec in specs.items():
                cs._deployments[name] = ClusterDeployment.from_spec(spec)
        live = pool.live_nodes()
        listings: Dict[str, Dict[str, Any]] = {}
        for node_name, node in live.items():
            try:
                listings[node_name] = node.list_replicas()
            except Exception:
                listings[node_name] = {}
        adopted: List[ClusterReplica] = []
        for rid, p in sorted(placements.items()):
            dep = cs._deployments.get(p["deployment"])
            if dep is None:
                continue
            node_name = p["node"]
            hosted = listings.get(node_name, {}).get(rid)
            if hosted is not None and hosted.get("alive"):
                rep = ClusterReplica(rid, dep.name, node_name,
                                     hosted["address"],
                                     devices=int(p.get("devices") or 0))
                if p.get("gang_id") and node_name in live:
                    # re-own the surviving agent-side reservation so a
                    # later release (delete / node death) still frees it
                    rep.gang = GangReservation(
                        p["gang_id"], {live[node_name].address:
                                       live[node_name]},
                        {live[node_name].address: rep.devices})
                dep.replicas.append(rep)
                adopted.append(rep)
                pool.record_event("replica_adopted", deployment=dep.name,
                                  replica_id=rid, node=node_name)
                # keep ids monotonic past the adopted ones
                cs._bump_rid(dep.name, rid)
            else:
                pool.record_event("replica_removed", deployment=dep.name,
                                  replica_id=rid,
                                  reason="lost at recovery")
                cs._bump_rid(dep.name, rid)
                if p.get("gang_id") and node_name in live:
                    # the replica died but its AGENT survived: the
                    # agent's in-memory reservation is still holding
                    # the dead replica's dp*tp slots — release it or
                    # the node's capacity is leaked until agent restart
                    GangReservation(
                        p["gang_id"],
                        {live[node_name].address: live[node_name]},
                        {live[node_name].address:
                         int(p.get("devices") or 0)}).release()
                try:
                    cs._place_one(dep, rid)
                except Exception as e:
                    pool.record_event("replica_lost",
                                      deployment=dep.name,
                                      replica_id=rid, error=repr(e))
        # fence the survivors under the NEW epoch: every agent and every
        # adopted replica advances its watermark, so the superseded
        # head's stamped writes (placements, adopt_seq, stops) are
        # rejected typed from here on — re-adoption IS the fencing point
        cs._fence_survivors(live, adopted)
        cs._push_table()
        return cs

    def _fence_survivors(self, live: Dict[str, RemoteNode],
                         adopted: Sequence[ClusterReplica]) -> None:
        from tosem_tpu.cluster.rpc import RpcClient
        epoch = self.epoch
        for node in live.values():
            try:
                node.fence(epoch)
            except Exception:
                pass            # unreachable agent: the detector's case
        for rep in adopted:
            try:
                with RpcClient(rep.address) as cli:
                    cli.call("fence", epoch)
            except Exception:
                pass            # dead replica: re-placement's case

    def _bump_rid(self, name: str, rid: str) -> None:
        """Advance the id counter past a journal-recovered replica id
        so fresh placements never collide with adopted ones."""
        try:
            n = int(rid.rsplit("#r", 1)[1])
        except (IndexError, ValueError):
            return
        self._rid_next[name] = max(self._rid_next.get(name, 0), n + 1)

    # -- telemetry -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Aggregate control+data-plane snapshot (the ``/-/stats``
        payload): per-deployment placements, per-router routed/spilled
        counters, and the per-node queue-depth rollup — mirrored into
        the driver registry's cluster gauges so one Prometheus scrape
        sees the whole tier."""
        with self._lock:
            deps = {name: {"replicas": len(dep.replicas),
                           "nodes": sorted({r.node for r in dep.replicas}),
                           "strategy": dep.strategy,
                           "sharding": (list(dep.sharding)
                                        if dep.sharding else None),
                           "placement": [r.info() for r in dep.replicas]}
                    for name, dep in self._deployments.items()}
            routers = list(self._routers)
            version = self._version
        router_stats: List[Dict[str, Any]] = []
        remote_stats: List[Dict[str, Any]] = []
        for router in routers:
            try:
                rs = router.stats()
            except Exception:
                rs = {"name": getattr(router, "name", "?"), "dead": True}
            router_stats.append(rs)
            if isinstance(router, RemoteRouter):
                remote_stats.append(rs)
        if self._scorer is not None:
            # serve-recency feeds the ledger's LRU order: a model whose
            # replicas show router-observed depth is HOT on its node,
            # whatever order placement warmed things in
            for rs in router_stats:
                for info in rs.get("replicas", {}).values():
                    if info.get("depth", 0) > 0:
                        self._scorer.ledger.touch(
                            info.get("node", "?"),
                            info.get("deployment", "?"))
        nodes: Dict[str, Dict[str, Any]] = {}
        routed = spilled = 0
        prefix_routed = prefix_transfers = 0
        for rs in router_stats:
            routed += rs.get("routed", 0)
            spilled += rs.get("spilled", 0)
            prefix_routed += rs.get("prefix_routed", 0)
            prefix_transfers += rs.get("prefix_transfers", 0)
            for node, depth in rs.get("node_queue_depth", {}).items():
                cur = nodes.setdefault(node, {"queue_depth": 0,
                                              "replicas": 0})
                # each router has its own (cached) view; the max is the
                # honest rollup — summing would count a request once
                # per router that saw it
                cur["queue_depth"] = max(cur["queue_depth"], depth)
        # export under the controller lock: /-/stats is served by a
        # threaded HTTP server, and a racing scrape must not double-
        # apply a mirrored counter delta or cross the departed-label
        # bookkeeping mid-update
        with self._lock:
            if self._metrics is None:
                from tosem_tpu.obs.metrics import cluster_serve_metrics
                self._metrics = cluster_serve_metrics()
            # mirror PROCESS routers' routed/spilled counters into the
            # driver registry by delta (their own registries have no
            # scrape endpoint; in-proc routers already feed this
            # registry directly — mirroring those would double-count)
            for rs in remote_stats:
                rname = rs.get("name", "?")
                for dep_name, paths in rs.get("requests", {}).items():
                    for path, total in paths.items():
                        mkey = (dep_name, rname, path)
                        delta = total - self._mirrored.get(mkey, 0)
                        if delta > 0:
                            self._metrics["router_requests"].inc(
                                delta, mkey)
                            self._mirrored[mkey] = total
            placed_now: set = set()
            for name, d in deps.items():
                per_node: Dict[str, int] = {}
                for r in d["placement"]:
                    per_node[r["node"]] = per_node.get(r["node"], 0) + 1
                for node, count in per_node.items():
                    nodes.setdefault(node, {"queue_depth": 0,
                                            "replicas": 0})
                    nodes[node]["replicas"] += count
                    self._metrics["replicas_placed"].set(count,
                                                         (name, node))
                    placed_now.add((name, node))
            for node, d in nodes.items():
                self._metrics["node_queue_depth"].set(d["queue_depth"],
                                                      (node,))
            # REMOVE series whose label sets departed (a dead node
            # keeping its last replica count/queue depth forever would
            # read as mass that failover never moved — and a permanent
            # zero row is just as stale: it reads as a live idle node
            # to every aggregation over the label)
            for name, node in self._exported_placed - placed_now:
                self._metrics["replicas_placed"].remove((name, node))
            for node in self._exported_nodes - set(nodes):
                self._metrics["node_queue_depth"].remove((node,))
            self._exported_placed = placed_now
            self._exported_nodes = set(nodes)
        return {"deployments": deps, "routers": router_stats,
                "nodes": nodes, "version": version,
                "routed": routed, "spilled": spilled,
                "prefix_routed": prefix_routed,
                "prefix_transfers": prefix_transfers}

    def close(self, stop_replicas: bool = True,
              close_pool: bool = False) -> None:
        with self._lock:
            self._closed = True
            deployments = list(self._deployments.values())
            self._deployments = {}
            routers = list(self._routers)
            self._routers = []
        if stop_replicas:
            for dep in deployments:
                self._teardown_deployment(dep)
        for router in routers:
            try:
                router.close()
            except Exception:
                pass
        if close_pool:
            self.pool.close(close_nodes=True)
