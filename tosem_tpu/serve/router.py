"""Router tier for the cluster serving plane.

The reference's router actors load-balance requests over replica
workers and every proxy holds a full copy of the routing table
(``serve/router.py``); same shape here. A :class:`RouterCore` is
**stateless** beyond its routing table (pushed by the controller with a
version number) and soft load caches, so routers are replicated freely:
clients hold several router addresses and fail over — a dead router
loses nothing but the requests inside it, and those are retried by the
client on a surviving router.

Routing policy (per request):

- ``key=None`` → least-loaded over the cached per-replica queue
  depths (round-robin tiebreak).
- ``key=...`` → consistent hashing over the deployment's replica ring
  (compile-cache / KV affinity: one session's requests keep landing on
  the replica whose caches are warm), **spilling over** to the
  least-loaded replica when the primary's queue depth exceeds
  ``spill_depth`` and someone else is meaningfully idler — affinity is
  a preference, not a hostage situation.

Load signal: every replica response carries the replica's in-flight
depth (see :mod:`tosem_tpu.serve.replica_worker`), so the cache
refreshes for free on the data path; an explicit scrape only happens
for replicas idle longer than ``scrape_ttl_s``.

Failure semantics: a transport error (dead replica/node) excludes that
replica locally and retries the request on the remaining replicas —
re-admission from step 0, exact for the deterministic backends (greedy
decode, padded-program encode). The per-deployment breaker sees ONE
failure per logical request whatever the attempt count, mirroring the
PR-5/6 logical-request accounting. Application errors are never
retried and surface to the caller typed.

SLO admission (the control-plane PR): deployments with an
:class:`~tosem_tpu.control.admission.SLOConfig` pushed alongside the
routing table run every request through an estimated-wait check and a
priority-class dispatch gate BEFORE the breaker — overload rejects
typed (:class:`~tosem_tpu.control.admission.Overloaded`, with
``retry_after``) instead of queueing into a breaker trip, decode-class
requests preempt bulk encode in the wait queue, and per-class shed
counters feed ``serve_admission_shed_total`` and ``/-/stats``.
"""
from __future__ import annotations

import hashlib
import os
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from tosem_tpu.chaos import network as _net
from tosem_tpu.runtime.common import DeadlineExceeded
from tosem_tpu.serve.breaker import CircuitBreaker, CircuitOpen

VNODES = 32          # hash-ring points per replica
_HEDGE_POOL_WORKERS = 16   # reusable hedge-dispatch threads per router


class NoReplicaAvailable(RuntimeError):
    """Every replica in the routing table failed (or none exist) for
    this request — the router-level analog of NodeLostError. NOT a
    ConnectionError subclass: the RPC server swallows ConnectionError
    (peer-gone handling in ``RpcServer._serve_conn``) instead of
    shipping it, and the client handle must distinguish 'no replicas'
    (typed verdict, surface it) from 'this router is dead' (fail over
    to the next router)."""


class ReplicaAppError(RuntimeError):
    """The backend raised while handling the request (application
    error: not retried; carries the remote repr)."""


def _hash64(s: str) -> int:
    return int.from_bytes(hashlib.md5(s.encode()).digest()[:8], "big")


class _Link:
    """Per-replica soft state (cached depth, dead mark) + clients.

    Clients are PER-THREAD: an RpcClient admits one in-flight call at
    a time (it holds its lock across the whole round trip), so a
    shared client would cap the router at one concurrent request per
    replica — defeating the replica's thread-per-connection server —
    and would head-of-line-block a depth scrape behind an unrelated
    in-flight call. The register keeps every thread's client reachable
    for close()."""

    def __init__(self, info: Dict[str, Any]):
        self.info = dict(info)
        self.address = info["address"]
        self._tls = threading.local()
        self._clients: List[Any] = []
        self._clients_lock = threading.Lock()
        self.depth = 0
        self.depth_ts = 0.0
        self.dead = False
        # last prefix digest this replica piggybacked on a response:
        # bounded [depth, n_tokens, hash] triples of its hottest
        # cached prefixes (None until the first decode response)
        self.prefixes = None

    def client(self):
        from tosem_tpu.cluster.rpc import RpcClient
        cli = getattr(self._tls, "client", None)
        if cli is None:
            cli = RpcClient(self.address)
            self._tls.client = cli
            with self._clients_lock:
                self._clients.append(cli)
        return cli

    def close(self) -> None:
        with self._clients_lock:
            clients, self._clients = self._clients, []
        for cli in clients:
            cli.close()


class RouterPolicy:
    """Routing knobs (one object so the bench/chaos scenarios and the
    controller construct routers identically; serializes through the
    router process boundary via to_json/from_json so the knobs an
    operator configures actually reach process routers)."""

    def __init__(self, spill_depth: int = 4, scrape_ttl_s: float = 0.25,
                 failure_threshold: int = 8, cooldown_s: float = 2.0,
                 hedge_after_s: float = 0.0, hedge_quantile: float = 0.95,
                 hedge_min_samples: int = 8, prefix_routing: bool = True):
        self.spill_depth = spill_depth
        self.scrape_ttl_s = scrape_ttl_s
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        # prefix-aware routing: un-keyed decode requests prefer the
        # replica whose piggybacked digest holds their longest token
        # prefix (depth still wins: an overloaded owner spills to
        # least-loaded as usual, with a best-effort worker→worker
        # prefix transfer to the replica that got the request instead)
        self.prefix_routing = prefix_routing
        # hedging (Dean, "The Tail at Scale"): hedge_after_s > 0 arms
        # it — a request still in flight after the hedge delay is
        # re-dispatched to a SECOND replica, first success wins. The
        # delay starts at hedge_after_s and, once hedge_min_samples
        # latencies are observed for a deployment, becomes that
        # deployment's hedge_quantile latency — so hedges fire only in
        # the tail the fleet itself defines, bounding the extra load to
        # ~(1 - quantile) of traffic
        self.hedge_after_s = hedge_after_s
        self.hedge_quantile = hedge_quantile
        self.hedge_min_samples = max(1, int(hedge_min_samples))

    def to_json(self) -> str:
        import json
        return json.dumps({"spill_depth": self.spill_depth,
                           "scrape_ttl_s": self.scrape_ttl_s,
                           "failure_threshold": self.failure_threshold,
                           "cooldown_s": self.cooldown_s,
                           "hedge_after_s": self.hedge_after_s,
                           "hedge_quantile": self.hedge_quantile,
                           "hedge_min_samples": self.hedge_min_samples,
                           "prefix_routing": self.prefix_routing},
                          sort_keys=True)

    @classmethod
    def from_json(cls, blob: str) -> "RouterPolicy":
        import json
        return cls(**json.loads(blob))


class RouterCore:
    """One router's logic — embeddable in-process (tests, the driver)
    or behind :func:`serve_router` as its own process."""

    def __init__(self, name: str = "router0",
                 policy: Optional[RouterPolicy] = None):
        self.name = name
        self.policy = policy or RouterPolicy()
        self._lock = threading.Lock()
        self._version = -1
        self._table: Dict[str, List[_Link]] = {}
        self._rings: Dict[str, List[Tuple[int, _Link]]] = {}
        self._rr = 0
        self._breakers: Dict[str, CircuitBreaker] = {}
        # SLO admission state per deployment (configs pushed with the
        # routing table; absent deployment = no admission, the
        # pre-control-plane behavior)
        self._admission: Dict[str, Any] = {}
        self._routed = 0          # affinity/least-loaded picks honored
        self._spilled = 0         # affinity overridden by queue depth
        self._retried = 0         # transport-failure re-dispatches
        self._errors = 0          # logical requests ultimately failed
        self._hedged = 0          # hedge attempts launched
        self._hedge_wins = 0      # hedge attempts whose result was used
        self._deadline_shed = 0   # requests shed expired before dispatch
        self._prefix_routed = 0   # picks overridden by a prefix match
        self._prefix_transfers = 0       # worker→worker prefix pulls
        self._prefix_transfer_fails = 0  # pulls that fell back cold
        # per-deployment latency rings feeding the quantile-derived
        # hedge delay; suspects: node names the controller de-preferences
        self._latency: Dict[str, deque] = {}
        self._hedge_pool = None
        # admission gate for the hedge pool: one permit per pool
        # thread, so an attempt either starts immediately or spills to
        # a one-shot thread — it never queues behind abandoned losers
        # still sleeping out a gray replica's latency
        self._hedge_slots = threading.Semaphore(_HEDGE_POOL_WORKERS)
        # per-(deployment, path) totals: what the controller mirrors
        # into the DRIVER registry for process routers (whose own
        # registries no scrape endpoint serves)
        self._dep_counts: Dict[Tuple[str, str], int] = {}
        self._metrics = None

    # -- control plane -------------------------------------------------

    def update_table(self, table: Dict[str, List[Dict[str, Any]]],
                     version: int,
                     admission: Optional[Dict[str, Dict[str, Any]]] = None
                     ) -> bool:
        """Install a routing table push. Stale versions are ignored
        (controller pushes can race over different router connections).
        Links are kept per address so cached depths survive a push;
        dead marks clear — the controller believes these addresses are
        alive, and a wrong belief costs one retried request.
        ``admission`` maps deployment → serialized
        :class:`~tosem_tpu.control.admission.SLOConfig`; replica-count
        changes resize each deployment's dispatch gate in place (wait
        queues survive the push)."""
        with self._lock:
            if version <= self._version:
                return False
            old_pairs = [(dep, lk) for dep, links in self._table.items()
                         for lk in links]
            old = {lk.address: lk for _, lk in old_pairs}
            new_table: Dict[str, List[_Link]] = {}
            rings: Dict[str, List[Tuple[int, _Link]]] = {}
            for dep, infos in table.items():
                links = []
                for info in infos:
                    lk = old.get(info["address"])
                    if lk is None:
                        lk = _Link(info)
                    else:
                        lk.info = dict(info)
                        lk.dead = False
                    links.append(lk)
                new_table[dep] = links
                ring = [(_hash64(f"{lk.info['replica_id']}#{v}"), lk)
                        for lk in links for v in range(VNODES)]
                rings[dep] = sorted(ring, key=lambda p: p[0])
            kept = {lk.address
                    for links in new_table.values() for lk in links}
            dropped = [(dep, lk) for dep, lk in old_pairs
                       if lk.address not in kept]
            for _, lk in {lk.address: (dep, lk)
                          for dep, lk in dropped}.values():
                lk.close()
            self._table = new_table
            self._rings = rings
            self._version = version
            self._update_admission_locked(table, admission)
        # REMOVE the departed replicas' depth series OUTSIDE the lock —
        # a gauge that keeps a dead replica's row (even at zero) forever
        # reads as a live-but-idle replica on a node that may no longer
        # exist
        m = self._metrics_dict()
        for dep, lk in dropped:
            m["replica_queue_depth"].remove(
                (dep, lk.info.get("node", "?"),
                 lk.info.get("replica_id", lk.address)))
        return True

    def _update_admission_locked(
            self, table: Dict[str, List[Dict[str, Any]]],
            admission: Optional[Dict[str, Dict[str, Any]]]) -> None:
        """Refresh per-deployment admission controllers against the new
        table. ``admission=None`` keeps the existing configs (a plain
        table push must not drop the SLOs installed by an earlier one);
        deployments that left the table lose their state."""
        from tosem_tpu.control.admission import (AdmissionController,
                                                 SLOConfig)
        shards: Dict[str, int] = {}
        if admission is not None:
            for dep, cfg in admission.items():
                cur = self._admission.get(dep)
                slo = SLOConfig.from_dict(cfg)
                shards[dep] = max(1, int(cfg.get("_shards", 1)))
                if cur is None or cur.slo.to_dict() != slo.to_dict():
                    self._admission[dep] = AdmissionController(
                        dep, slo, replicas=len(table.get(dep, ())) or 1,
                        shards=shards[dep],
                        on_shed=self._make_shed_observer(dep))
            for dep in [d for d in self._admission
                        if d not in admission]:
                del self._admission[dep]
        for dep, adm in self._admission.items():
            if dep in table:
                adm.update_replicas(len(table[dep]) or 1,
                                    shards=shards.get(dep))

    def _make_shed_observer(self, dep: str):
        def observe(klass: str, reason: str) -> None:
            self._metrics_dict()["admission_shed"].inc(
                1.0, (dep, klass, reason))
        return observe

    def table_version(self) -> int:
        with self._lock:
            return self._version

    def health(self) -> Dict[str, Any]:
        return {"ok": True, "pid": os.getpid(), "name": self.name}

    # -- picks ---------------------------------------------------------

    def _fresh_depth(self, lk: _Link) -> int:
        """Cached depth, scraping only when stale (idle replicas stop
        piggybacking, so a bounded scrape keeps the view honest)."""
        now = time.monotonic()
        if now - lk.depth_ts <= self.policy.scrape_ttl_s or lk.dead:
            return lk.depth
        try:
            lk.depth = int(lk.client().call("load"))
            lk.depth_ts = now
        except Exception:
            pass        # stale depth is fine; route() handles dead links
        return lk.depth

    def _least_loaded(self, links: List[_Link], exclude: set) -> _Link:
        live = [lk for lk in links
                if lk.address not in exclude and not lk.dead]
        if not live:
            # every replica is marked dead/tried: fall back to anything
            # not yet tried this request — a restarted replica at an old
            # address answers, a corpse fails fast into the next retry
            live = [lk for lk in links if lk.address not in exclude]
        if not live:
            raise NoReplicaAvailable("all replicas tried")
        with self._lock:
            self._rr += 1
            order = self._rr
        # least-loaded with round-robin tiebreak: equal-depth replicas
        # share fresh traffic instead of one absorbing it all. Replicas
        # on SUSPECT nodes (failure detector missed a probe — gray, not
        # yet dead) rank behind every healthy one: they still serve as
        # a last resort, but fresh traffic prefers nodes answering
        # their heartbeats
        n = len(live)
        i = min(range(n), key=lambda j: (
            1 if live[j].info.get("suspect") else 0,
            self._fresh_depth(live[j]), (j - order) % n))
        return live[i]

    def _pick(self, dep: str, key: Optional[str],
              exclude: set) -> Tuple[_Link, bool]:
        """(link, spilled?) for one attempt."""
        with self._lock:
            links = list(self._table.get(dep, ()))
            ring = self._rings.get(dep, ())
        if not links:
            raise NoReplicaAvailable(f"no replicas for deployment {dep!r}")
        if key is None:
            return self._least_loaded(links, exclude), False
        h = _hash64(str(key))
        primary = None
        if ring:
            # first ring point clockwise of the key's hash
            lo, hi = 0, len(ring)
            while lo < hi:
                mid = (lo + hi) // 2
                if ring[mid][0] < h:
                    lo = mid + 1
                else:
                    hi = mid
            primary = ring[lo % len(ring)][1]
        if (primary is not None and primary.address not in exclude
                and not primary.dead):
            if primary.info.get("suspect"):
                # affinity defers to suspicion: a warm cache on a node
                # that stopped answering heartbeats is not worth the
                # gray-latency risk — spill to a healthy replica and
                # let a cleared suspicion restore affinity
                best = self._least_loaded(links, exclude)
                if best is not primary:
                    return best, True
                return primary, False
            depth = self._fresh_depth(primary)
            if depth < self.policy.spill_depth:
                return primary, False
            best = self._least_loaded(links, exclude)
            if best is not primary and self._fresh_depth(best) < depth:
                return best, True       # spillover: affinity overridden
            return primary, False
        return self._least_loaded(links, exclude), False

    # -- prefix-aware routing ------------------------------------------

    def _prefix_match(self, links: List[_Link], ids) -> Optional[tuple]:
        """Deepest piggybacked digest entry that prefixes ``ids``
        while leaving >= 1 suffix token: ``(link, depth, n_tokens,
        hash)``, or None. Each candidate length hashes once however
        many replicas advertise it."""
        from tosem_tpu.serve.prefix_cache import prefix_hash
        best = None
        hashed: Dict[int, str] = {}
        for lk in links:
            if lk.dead or not lk.prefixes:
                continue
            for ent in lk.prefixes:
                try:
                    depth, n_tok, h = (int(ent[0]), int(ent[1]),
                                       str(ent[2]))
                except (TypeError, ValueError, IndexError):
                    continue
                if not 0 < n_tok < len(ids):
                    continue
                if best is not None and n_tok <= best[2]:
                    continue
                want = hashed.get(n_tok)
                if want is None:
                    want = hashed[n_tok] = prefix_hash(ids[:n_tok])
                if want == h:
                    best = (lk, depth, n_tok, h)
        return best

    def _apply_prefix_routing(self, deployment: str, request: Any,
                              key: Optional[str], lk: _Link,
                              spilled: bool,
                              tried: set) -> Tuple[_Link, bool]:
        """Longest-prefix override of one pick. An un-keyed decode
        request reroutes to the replica advertising its deepest cached
        prefix — unless that owner is suspect or past ``spill_depth``
        (load still wins, exactly like affinity spill). When the pick
        stands but another replica owns the prefix (keyed affinity, or
        an overloaded owner), the matched pages are pulled worker→
        worker into the picked replica first, so its admit prefills
        only the suffix instead of recomputing the whole prompt."""
        if not self.policy.prefix_routing or not isinstance(request, dict):
            return lk, spilled
        ids = request.get("ids")
        if not isinstance(ids, (list, tuple)) or len(ids) < 2:
            return lk, spilled
        with self._lock:
            links = [l for l in self._table.get(deployment, ())
                     if l.address not in tried]
        best = self._prefix_match(links, ids)
        if best is None or best[0] is lk:
            return lk, spilled
        owner, depth, _, h = best
        if (key is None and not owner.info.get("suspect")
                and self._fresh_depth(owner) < self.policy.spill_depth):
            with self._lock:
                self._prefix_routed += 1
            return owner, spilled
        self._transfer_prefix(owner, lk, depth, h)
        return lk, spilled

    def _transfer_prefix(self, owner: _Link, dst: _Link, depth: int,
                         h: str) -> None:
        """Best-effort worker→worker prefix pull (owner streams the
        pages to ``dst``'s tensor receiver, ``dst`` indexes them).
        Failure just means a cold prefill — never the request's
        verdict."""
        try:
            addr = getattr(dst, "_transport_addr", None)
            if addr is None:
                addr = dst.client().call("backend_call",
                                         "transport_address")
                dst._transport_addr = addr
            owner.client().call("backend_call", "send_prefix",
                                depth, h, addr)
            dst.client().call("backend_call", "adopt_prefix", h)
            with self._lock:
                self._prefix_transfers += 1
        except Exception:
            with self._lock:
                self._prefix_transfer_fails += 1

    # -- data plane ----------------------------------------------------

    def _breaker(self, dep: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(dep)
            if br is None:
                br = self._breakers[dep] = CircuitBreaker(
                    failure_threshold=self.policy.failure_threshold,
                    cooldown_s=self.policy.cooldown_s)
            return br

    def route(self, deployment: str, request: Any,
              key: Optional[str] = None,
              klass: Optional[str] = None,
              timeout_s: Optional[float] = None) -> Any:
        """Route one logical request; returns the backend's value.
        ``klass`` names the request's priority class for deployments
        with SLO admission (unknown/None ranks 0 — bulk).

        ``timeout_s`` is the request's end-to-end deadline budget:
        expired work sheds as typed :class:`DeadlineExceeded` BEFORE
        dispatch (and before admission — a request nobody is waiting
        for must not occupy an admission slot or a replica), and every
        retry re-checks the remaining budget."""
        if timeout_s is not None and timeout_s <= 0:
            with self._lock:
                self._deadline_shed += 1
            raise DeadlineExceeded(
                f"request to {deployment!r} arrived with an expired "
                f"deadline budget ({timeout_s:.3f}s)")
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self._lock:
            adm = self._admission.get(deployment)
        if adm is None:
            return self._route_admitted(deployment, request, key,
                                        deadline=deadline)
        # admission BEFORE the breaker: a shed is a typed capacity
        # verdict (Overloaded, retry_after), not backend-failure
        # evidence — it must neither trip the breaker nor occupy a
        # half-open probe slot
        adm.admit(klass)               # may raise Overloaded
        try:
            return self._route_admitted(deployment, request, key,
                                        deadline=deadline)
        finally:
            adm.release()

    # -- dispatch helpers ----------------------------------------------

    def _call_replica(self, lk: _Link, request: Any) -> Dict[str, Any]:
        """One dispatch to one replica. The emulated network's
        slow-node fault applies HERE — gray latency on the wire to a
        slow node's replicas, which is exactly the tail the hedge
        delay must cover."""
        gray = _net.state().delay(lk.info.get("node", ""))
        if gray > 0:
            time.sleep(gray)
        return lk.client().call("call", request)

    def _hedge_delay(self, deployment: str) -> Optional[float]:
        """None when hedging is disarmed; otherwise the current hedge
        delay — the policy floor until enough latencies are observed,
        then the deployment's own hedge_quantile latency."""
        if self.policy.hedge_after_s <= 0:
            return None
        with self._lock:
            ring = self._latency.get(deployment)
            samples = sorted(ring) if ring else []
        if len(samples) < self.policy.hedge_min_samples:
            return self.policy.hedge_after_s
        q = min(max(self.policy.hedge_quantile, 0.0), 1.0)
        idx = min(len(samples) - 1, int(q * len(samples)))
        return max(samples[idx], 1e-4)

    def _record_latency(self, deployment: str, elapsed: float) -> None:
        with self._lock:
            ring = self._latency.get(deployment)
            if ring is None:
                ring = self._latency[deployment] = deque(maxlen=128)
            ring.append(elapsed)

    def _call_hedged(self, deployment: str, lk: _Link, spilled: bool,
                     request: Any, tried: set, delay: float,
                     deadline: Optional[float]):
        """First-wins hedged dispatch: launch the primary, wait the
        hedge delay, and if it has not returned launch ONE hedge on a
        different replica. The first SUCCESS wins; the loser is
        abandoned (its late result is discarded — duplicate-retire is
        safe because the data-plane backends are idempotent per
        request: deterministic encode/decode, per-(seq, step) outcome
        ledgers on the stateful paths). Returns ``(out, winner_link,
        spilled, attempt_s)`` — ``attempt_s`` is the winning attempt's
        own dispatch latency; on total failure re-raises with every
        corpse marked so the outer retry loop moves on."""
        cv = threading.Condition()
        outcomes: List[tuple] = []

        def attempt(link: _Link) -> None:
            a0 = time.monotonic()
            try:
                res = (link, True, self._call_replica(link, request),
                       time.monotonic() - a0)
            except BaseException as e:
                res = (link, False, e, 0.0)
            with cv:
                outcomes.append(res)
                cv.notify_all()

        self._dispatch_attempt(attempt, lk)
        wait = delay
        if deadline is not None:
            wait = min(wait, max(0.0, deadline - time.monotonic()))
        with cv:
            cv.wait_for(lambda: outcomes, timeout=wait)
            launched = 1
        if not outcomes:
            second = None
            try:
                second, _ = self._pick(deployment, None,
                                       tried | {lk.address})
            except NoReplicaAvailable:
                second = None
            if second is not None and second.address != lk.address:
                with self._lock:
                    self._hedged += 1
                self._metrics_dict()["router_hedges"].inc(
                    1.0, (deployment, "fired"))
                self._dispatch_attempt(attempt, second)
                launched = 2
        while True:
            with cv:
                wins = [o for o in outcomes if o[1]]
                if wins:
                    winner = wins[0]
                    break
                if len(outcomes) >= launched:
                    winner = None
                    break
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    with self._lock:
                        self._deadline_shed += 1
                    raise DeadlineExceeded(
                        f"request to {deployment!r} exceeded its "
                        "deadline budget mid-hedge")
                cv.wait(timeout=remaining)
        if winner is not None:
            wlk = winner[0]
            if launched == 2 and wlk is not lk:
                with self._lock:
                    self._hedge_wins += 1
                self._metrics_dict()["router_hedges"].inc(
                    1.0, (deployment, "won"))
            # the ring gets the winning ATTEMPT's latency, not the
            # client-observed total: a hedged winner's total embeds the
            # hedge delay itself, and a quantile fed its own delay
            # ratchets upward until hedging self-disables
            return winner[2], wlk, spilled, winner[3]
        # every launched attempt failed: mark transport corpses, then
        # surface an application error if one occurred (never retried),
        # else the primary's transport error (outer loop retries)
        app_err = None
        conn_err = None
        for link, _ok, exc, _t in outcomes:
            if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
                link.dead = True
                tried.add(link.address)
                conn_err = conn_err or exc
            else:
                app_err = app_err or exc
        raise app_err or conn_err

    def _pool(self):
        """Lazy dispatch pool for hedged attempts (worker threads are
        reused, so per-thread RPC clients are too)."""
        with self._lock:
            if self._hedge_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                self._hedge_pool = ThreadPoolExecutor(
                    max_workers=_HEDGE_POOL_WORKERS,
                    thread_name_prefix=f"tosem-hedge-{self.name}")
            return self._hedge_pool

    def _dispatch_attempt(self, fn, link) -> None:
        """Start one hedged attempt WITHOUT ever queueing it. Pool
        threads are preferred (reused RPC clients), but a loser
        abandoned on a gray replica holds its thread for that replica's
        full latency — under a slow-node fault the pool fills with
        sleeping corpses, and a queued PRIMARY would inherit their
        delay, re-creating the very tail hedging exists to cut. When no
        pool permit is free the attempt runs on a one-shot thread
        instead."""
        if self._hedge_slots.acquire(blocking=False):
            def run(lk=link):
                try:
                    fn(lk)
                finally:
                    self._hedge_slots.release()
            self._pool().submit(run)
        else:
            threading.Thread(
                target=fn, args=(link,), daemon=True,
                name=f"tosem-hedge-spill-{self.name}").start()

    def _route_admitted(self, deployment: str, request: Any,
                        key: Optional[str] = None,
                        deadline: Optional[float] = None) -> Any:
        br = self._breaker(deployment)
        probe = br.allow()              # may raise CircuitOpen
        tried: set = set()
        t0 = time.monotonic()
        try:
            while True:
                if deadline is not None and time.monotonic() >= deadline:
                    # budget burnt walking corpses: shed typed, no
                    # breaker verdict (a deadline is the CALLER's
                    # constraint, not backend-failure evidence)
                    with self._lock:
                        self._deadline_shed += 1
                    raise DeadlineExceeded(
                        f"request to {deployment!r} exceeded its "
                        "deadline budget before dispatch")
                try:
                    lk, spilled = self._pick(deployment, key, tried)
                    lk, spilled = self._apply_prefix_routing(
                        deployment, request, key, lk, spilled, tried)
                except NoReplicaAvailable:
                    with self._lock:
                        self._errors += 1
                    br.record_failure(probe=probe)
                    probe = False
                    raise
                hedge_delay = self._hedge_delay(deployment)
                attempt_s = None
                try:
                    if hedge_delay is None:
                        out = self._call_replica(lk, request)
                    else:
                        out, lk, spilled, attempt_s = self._call_hedged(
                            deployment, lk, spilled, request, tried,
                            hedge_delay, deadline)
                except DeadlineExceeded:
                    raise
                except (ConnectionError, TimeoutError, OSError):
                    # transport loss: the replica (or its node) is gone.
                    # Exclude it locally — the controller's next table
                    # push re-homes it — and re-admit the request from
                    # step 0 on a survivor. One logical request, one
                    # eventual breaker verdict (below), however many
                    # corpses it walked past.
                    lk.dead = True
                    tried.add(lk.address)
                    with self._lock:
                        self._retried += 1
                    continue
                except Exception as e:
                    # application error (RpcError): the backend itself
                    # failed this request — never retried, one breaker
                    # trip, typed for the caller
                    with self._lock:
                        self._errors += 1
                    br.record_failure(probe=probe)
                    probe = False
                    raise ReplicaAppError(str(e)) from None
                lk.depth = int(out.get("load", 0))
                lk.depth_ts = time.monotonic()
                prefixes = out.get("prefixes")
                if prefixes is not None:
                    lk.prefixes = prefixes
                with self._lock:
                    if spilled:
                        self._spilled += 1
                    else:
                        self._routed += 1
                    ckey = (deployment,
                            "spilled" if spilled else "routed")
                    self._dep_counts[ckey] = \
                        self._dep_counts.get(ckey, 0) + 1
                br.record_success(probe=probe)
                probe = False
                self._record_latency(
                    deployment,
                    attempt_s if attempt_s is not None
                    else time.monotonic() - t0)
                self._observe(deployment, lk, spilled)
                return out["value"]
        except BaseException:
            if probe:
                # a probe abandoned WITHOUT a verdict (an unexpected
                # raise before any record call) must not wedge the
                # breaker half-open; probe flips False the moment a
                # record call consumes it, so this can never free a
                # slot some other request now owns
                br.release_probe()
            raise

    # -- telemetry -----------------------------------------------------

    def _metrics_dict(self):
        if self._metrics is None:
            from tosem_tpu.obs.metrics import cluster_serve_metrics
            self._metrics = cluster_serve_metrics()
        return self._metrics

    def _observe(self, deployment: str, lk: _Link, spilled: bool) -> None:
        """Feed the cluster serving instruments in THIS router's
        process registry (the driver's, for in-proc routers)."""
        m = self._metrics_dict()
        info = lk.info
        m["router_requests"].inc(
            1.0, (deployment, self.name, "spilled" if spilled else "routed"))
        m["replica_queue_depth"].set(
            lk.depth, (deployment, info.get("node", "?"),
                       info.get("replica_id", lk.address)))

    def stats(self) -> Dict[str, Any]:
        """Router-tier snapshot: routed-vs-spilled counters plus the
        per-node queue-depth rollup the controller aggregates."""
        with self._lock:
            links = [(dep, lk) for dep, ls in self._table.items()
                     for lk in ls]
            out = {"name": self.name, "version": self._version,
                   "routed": self._routed, "spilled": self._spilled,
                   "retried": self._retried, "errors": self._errors,
                   "hedged": self._hedged,
                   "hedge_wins": self._hedge_wins,
                   "deadline_shed": self._deadline_shed,
                   "prefix_routed": self._prefix_routed,
                   "prefix_transfers": self._prefix_transfers,
                   "prefix_transfer_fails":
                       self._prefix_transfer_fails}
            requests: Dict[str, Dict[str, int]] = {}
            for (dep, path), n in self._dep_counts.items():
                requests.setdefault(dep, {})[path] = n
            out["requests"] = requests
            out["admission"] = {dep: adm.stats()
                                for dep, adm in self._admission.items()}
        per_node: Dict[str, int] = {}
        replicas = {}
        for dep, lk in links:
            node = lk.info.get("node", "?")
            per_node[node] = per_node.get(node, 0) + lk.depth
            replicas[lk.info.get("replica_id", lk.address)] = {
                "deployment": dep, "node": node, "depth": lk.depth,
                "dead": lk.dead}
        out["node_queue_depth"] = per_node
        out["replicas"] = replicas
        return out

    def close(self) -> None:
        with self._lock:
            links = [lk for ls in self._table.values() for lk in ls]
            pool, self._hedge_pool = self._hedge_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        for lk in links:
            lk.close()


# --------------------------------------------------------- process entry


def serve_router(port: int = 0, announce_fd: Optional[int] = None,
                 name: str = "router",
                 lifeline_fd: Optional[int] = None,
                 policy: Optional[RouterPolicy] = None) -> None:
    """Run one router until killed, or until the lifeline pipe hits
    EOF (the write end lives in the spawning controller — a crashed
    driver must not leave orphan routers; same contract as
    :mod:`tosem_tpu.serve.replica_worker`)."""
    from tosem_tpu.cluster.rpc import RpcServer
    core = RouterCore(name=name, policy=policy)
    server = RpcServer(core, port=port)
    line = f"{server.address}\n".encode()
    if announce_fd is not None:
        os.write(announce_fd, line)
        os.close(announce_fd)
    else:
        sys.stdout.write(line.decode())
        sys.stdout.flush()
    try:
        if lifeline_fd is not None:
            while os.read(lifeline_fd, 1):
                pass
        else:
            while True:
                time.sleep(3600)
    except (KeyboardInterrupt, OSError):
        pass
    finally:
        server.shutdown()
        core.close()


def main(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    port, announce_fd, lifeline_fd, name = 0, None, None, "router"
    policy: Optional[RouterPolicy] = None
    i = 0
    while i < len(args):
        if args[i] == "--port":
            port = int(args[i + 1]); i += 2
        elif args[i] == "--announce-fd":
            announce_fd = int(args[i + 1]); i += 2
        elif args[i] == "--lifeline-fd":
            lifeline_fd = int(args[i + 1]); i += 2
        elif args[i] == "--name":
            name = args[i + 1]; i += 2
        elif args[i] == "--policy":
            policy = RouterPolicy.from_json(args[i + 1]); i += 2
        else:
            print(f"unknown arg {args[i]}", file=sys.stderr)
            return 2
    serve_router(port=port, announce_fd=announce_fd, name=name,
                 lifeline_fd=lifeline_fd, policy=policy)
    return 0


class RemoteRouter:
    """Driver/client-side handle to a router process.

    ``route`` uses a per-thread client: a 16-thread client fleet must
    pipeline through the router's thread-per-connection server, not
    serialize on one socket's in-flight lock."""

    def __init__(self, address: str, name: str = "router"):
        self.address = address
        self.name = name
        self._proc: Optional[subprocess.Popen] = None
        self._lifeline: Optional[int] = None
        self._tls = threading.local()
        self._control = None
        self._control_lock = threading.Lock()

    def _client(self):
        from tosem_tpu.cluster.rpc import RpcClient
        cli = getattr(self._tls, "client", None)
        if cli is None:
            cli = self._tls.client = RpcClient(self.address)
        return cli

    def _ctl(self):
        from tosem_tpu.cluster.rpc import RpcClient
        with self._control_lock:
            if self._control is None:
                self._control = RpcClient(self.address)
            return self._control

    # data plane (per-thread connection)
    def route(self, deployment: str, request: Any,
              key: Optional[str] = None,
              klass: Optional[str] = None,
              timeout_s: Optional[float] = None) -> Any:
        return self._client().call("route", deployment, request, key,
                                   klass, timeout_s)

    # control plane (shared connection; controller is single-threaded
    # per router)
    def update_table(self, table: Dict[str, Any], version: int,
                     admission: Optional[Dict[str, Any]] = None) -> bool:
        return bool(self._ctl().call("update_table", table, version,
                                     admission))

    def stats(self) -> Dict[str, Any]:
        return self._ctl().call("stats")

    def table_version(self) -> int:
        return int(self._ctl().call("table_version"))

    def alive(self, timeout: float = 5.0) -> bool:
        from tosem_tpu.cluster.rpc import RpcClient
        try:
            with RpcClient(self.address, timeout=timeout,
                           call_timeout=timeout) as probe:
                return bool(probe.call("health").get("ok"))
        except Exception:
            return False

    @classmethod
    def spawn_local(cls, name: str = "router",
                    startup_timeout: float = 60.0,
                    policy: Optional[RouterPolicy] = None
                    ) -> "RemoteRouter":
        """Boot a router subprocess on this host and connect to it.
        ``policy`` ships over argv — the knobs an operator configures
        on the controller must reach the process router, not silently
        fall back to defaults."""
        from tosem_tpu.cluster.node import die_with_parent, read_announce
        r, w = os.pipe()
        env = dict(os.environ)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        life_r, life_w = os.pipe()
        argv = [sys.executable, "-c",
                "from tosem_tpu.serve.router import main; main()",
                "--announce-fd", str(w), "--name", name,
                "--lifeline-fd", str(life_r)]
        if policy is not None:
            argv += ["--policy", policy.to_json()]
        proc = subprocess.Popen(argv, pass_fds=(w, life_r), env=env,
                                preexec_fn=die_with_parent)
        os.close(w)
        os.close(life_r)
        line = read_announce(r, startup_timeout)
        if not line.endswith(b"\n"):
            proc.kill()
            proc.wait()
            os.close(life_w)
            raise RuntimeError(f"router {name!r} failed to announce "
                               f"within {startup_timeout}s")
        router = cls(line.decode().strip(), name=name)
        router._proc = proc
        router._lifeline = life_w
        return router

    def kill(self) -> None:
        """Simulated router death (SIGKILL)."""
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait()
        self.close()

    def close(self) -> None:
        with self._control_lock:
            if self._control is not None:
                self._control.close()
                self._control = None
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        if self._lifeline is not None:
            try:
                os.close(self._lifeline)
            except OSError:
                pass
            self._lifeline = None
