"""Warm compiled-program cache for serving backends.

A cold replica's first request pays the full XLA JIT of its model — on
the north-star BERT shapes that is multi-second, which is exactly the
tail latency a serving layer exists to hide. This module is the
replica-side fix: a process-wide cache of AOT-lowered executables keyed
by ``(model, bucket shape, dtype)``, shared by every backend instance
(and therefore every replica thread) living in the same worker process.
``Serve.deploy(warmup_shapes=…)`` drives :meth:`CompileCache.get_or_build`
for each declared shape at deploy time, so replica 0's first real
request finds its program already compiled.

Design points:

- **Per-key build locks.** Two replica threads racing for the same
  bucket shape compile once; the loser blocks on the winner's build
  instead of duplicating a multi-second lowering (double-checked
  per-key locking, the memoization discipline XLA's own compilation
  cache uses).
- **AOT lowering.** :func:`aot_compile` goes through
  ``jax.jit(fn).lower(*specs).compile()`` so warming never touches real
  data — declared shapes become :class:`jax.ShapeDtypeStruct` specs.
- **Pinned-ledger LRU (multi-model multiplexing).** Many models share
  one process under a bounded ``budget`` of cached entries. Serving
  backends :meth:`pin` their model while they hold traffic; when an
  insert pushes the cache over budget, eviction walks coldest-model-
  first (LRU over whole models, not individual shapes — evicting one
  bucket of a live palette just re-pays its JIT piecemeal) and SKIPS
  pinned models — the object-store pin discipline applied to
  executables. A fully-pinned over-budget cache stays over budget
  rather than evicting out from under a serving replica.
- **Observable.** Hit/miss/build-time/eviction counters surface through
  :meth:`stats` and the deployment's ``/-/stats`` endpoint, so a bucket
  palette that quietly recompiles per request is visible.
"""
from __future__ import annotations

import threading
import time
from typing import (Any, Callable, Dict, Hashable, List, Optional,
                    Sequence, Tuple)


def _model_of(key: Hashable) -> Hashable:
    """The model component of a cache key — :func:`shape_key` tuples
    lead with the model tag; scalar keys ARE the model. Program-variant
    suffixes the backends append after the tag's closing paren
    (``…);step``, ``…);mask=<sig>`` — see ``model_tag``) are stripped,
    so every variant of one model forms ONE eviction group: evicting a
    model piecemeal would leave palette holes that re-pay their JIT
    one bucket at a time."""
    if isinstance(key, tuple) and key:
        key = key[0]
    if isinstance(key, str) and ");" in key:
        return key.split(");", 1)[0] + ")"
    return key


class CompileCache:
    """Thread-safe build-once cache (executables, or anything costly).

    ``budget``: maximum cached entries before LRU model eviction kicks
    in (None = unbounded, the pre-multiplexing behavior)."""

    def __init__(self, budget: Optional[int] = None):
        if budget is not None and budget < 1:
            raise ValueError("budget must be >= 1 (or None)")
        self.budget = budget
        self._lock = threading.Lock()
        self._entries: Dict[Hashable, Any] = {}
        self._building: Dict[Hashable, threading.Lock] = {}
        # model -> monotonically increasing last-use stamp (LRU order)
        self._model_used: Dict[Hashable, int] = {}
        self._use_clock = 0
        # model -> pin owners (serving replicas holding traffic)
        self._pins: Dict[Hashable, set] = {}
        self._hits = 0
        self._misses = 0
        self._build_s = 0.0
        self._evicted_entries = 0
        self._evicted_models = 0

    def _touch_locked(self, key: Hashable) -> None:
        self._use_clock += 1
        self._model_used[_model_of(key)] = self._use_clock

    def get(self, key: Hashable) -> Optional[Any]:
        with self._lock:
            value = self._entries.get(key)
            if value is not None:
                self._touch_locked(key)
            return value

    def get_or_build(self, key: Hashable, build: Callable[[], Any]) -> Any:
        """Return the cached value for ``key``, building it (once, even
        under concurrency) when absent."""
        with self._lock:
            if key in self._entries:
                self._hits += 1
                self._touch_locked(key)
                return self._entries[key]
            gate = self._building.setdefault(key, threading.Lock())
        with gate:
            # double-check: the winner of the race filled the entry
            # while we waited on its gate
            with self._lock:
                if key in self._entries:
                    self._hits += 1
                    self._touch_locked(key)
                    return self._entries[key]
            t0 = time.perf_counter()
            value = build()
            dt = time.perf_counter() - t0
            with self._lock:
                self._entries[key] = value
                self._misses += 1
                self._build_s += dt
                self._touch_locked(key)
                self._building.pop(key, None)
                self._evict_over_budget_locked(
                    protect=_model_of(key))
            return value

    # -- pinned-ledger model eviction ----------------------------------

    def pin(self, model: Hashable, owner: str = "replica") -> None:
        """``model`` is serving traffic for ``owner``: its entries are
        not eviction victims until every owner unpins."""
        with self._lock:
            self._pins.setdefault(model, set()).add(owner)

    def unpin(self, model: Hashable, owner: str = "replica") -> None:
        with self._lock:
            owners = self._pins.get(model)
            if owners is not None:
                owners.discard(owner)
                if not owners:
                    del self._pins[model]

    def pinned_models(self) -> List[Hashable]:
        with self._lock:
            return sorted(self._pins, key=repr)

    def _evict_model_locked(self, model: Hashable) -> int:
        victims = [k for k in self._entries if _model_of(k) == model]
        for k in victims:
            del self._entries[k]
        self._model_used.pop(model, None)
        if victims:
            self._evicted_entries += len(victims)
            self._evicted_models += 1
        return len(victims)

    def _evict_over_budget_locked(self,
                                  protect: Optional[Hashable] = None
                                  ) -> None:
        if self.budget is None:
            return
        while len(self._entries) > self.budget:
            cold = [m for m, _ in sorted(self._model_used.items(),
                                         key=lambda kv: kv[1])
                    if m != protect and not self._pins.get(m)]
            if not cold:
                return          # everything is pinned (or the inserting
            #                     model itself): over budget beats
            #                     evicting under a live replica
            self._evict_model_locked(cold[0])

    def evict_model(self, model: Hashable) -> int:
        """Explicitly drop every entry of ``model`` (refused while
        pinned). Returns the entry count evicted."""
        with self._lock:
            if self._pins.get(model):
                return 0
            return self._evict_model_locked(model)

    # -- queries -------------------------------------------------------

    def __contains__(self, key: Hashable) -> bool:
        with self._lock:
            return key in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._building.clear()
            self._model_used.clear()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"entries": len(self._entries), "hits": self._hits,
                    "misses": self._misses,
                    "build_s": round(self._build_s, 3),
                    "pinned_models": len(self._pins),
                    "evicted_entries": self._evicted_entries,
                    "evicted_models": self._evicted_models}


def _env_budget() -> Optional[int]:
    """TOSEM_COMPILE_CACHE_BUDGET, hardened: unset/0/garbage all mean
    unbounded — a config typo must not crash every serve import."""
    import os
    import sys
    raw = os.environ.get("TOSEM_COMPILE_CACHE_BUDGET", "").strip()
    if not raw:
        return None
    try:
        budget = int(raw)
    except ValueError:
        print(f"TOSEM_COMPILE_CACHE_BUDGET={raw!r} is not an integer; "
              "compile cache stays unbounded", file=sys.stderr)
        return None
    return budget if budget >= 1 else None


# One cache per process: replicas co-located in a worker share compiled
# programs; the driver process gets its own for in-process backends.
# TOSEM_COMPILE_CACHE_BUDGET bounds the entry count (multi-model
# multiplexing: cold models' executables make room for hot ones');
# unset = unbounded, the pre-control-plane behavior.
DEFAULT_COMPILE_CACHE = CompileCache(budget=_env_budget())


def shape_key(model: str, shape: Sequence[int], dtype: str) -> Tuple:
    """Canonical cache key: ``(model, (dims…), dtype)`` — the
    (model, bucket shape, dtype) triple of the design."""
    return (model, tuple(int(d) for d in shape), str(dtype))


def aot_compile(fn: Callable, arg_specs: Sequence[Tuple[Sequence[int], Any]],
                donate_argnums: Sequence[int] = ()) -> Any:
    """AOT-lower ``fn`` for the given ``(shape, dtype)`` specs and return
    the compiled executable (callable with concrete arrays of exactly
    those shapes). No real data is touched — safe for deploy-time
    warming. ``donate_argnums`` forwards to ``jax.jit`` — the decode
    backends donate their KV pools into the step/prefill programs so
    page writes land IN PLACE instead of copying the whole pool per
    step (at a 768-page pool the functional copy dominated the step;
    donated arguments must not be read after the call — the backends
    swap ``set_pools`` immediately)."""
    import jax
    specs = [jax.ShapeDtypeStruct(tuple(s), d) for s, d in arg_specs]
    return jax.jit(fn, donate_argnums=tuple(donate_argnums)).lower(
        *specs).compile()
