"""Warm compiled-program cache for serving backends.

A cold replica's first request pays the full XLA JIT of its model — on
the north-star BERT shapes that is multi-second, which is exactly the
tail latency a serving layer exists to hide. This module is the
replica-side fix: a process-wide cache of AOT-lowered executables keyed
by ``(model, bucket shape, dtype)``, shared by every backend instance
(and therefore every replica thread) living in the same worker process.
``Serve.deploy(warmup_shapes=…)`` drives :meth:`CompileCache.get_or_build`
for each declared shape at deploy time, so replica 0's first real
request finds its program already compiled.

Design points:

- **Per-key build locks.** Two replica threads racing for the same
  bucket shape compile once; the loser blocks on the winner's build
  instead of duplicating a multi-second lowering (double-checked
  per-key locking, the memoization discipline XLA's own compilation
  cache uses).
- **AOT lowering.** :func:`aot_compile` goes through
  ``jax.jit(fn).lower(*specs).compile()`` so warming never touches real
  data — declared shapes become :class:`jax.ShapeDtypeStruct` specs.
- **Observable.** Hit/miss/build-time counters surface through
  :meth:`stats` and the deployment's ``/-/stats`` endpoint, so a bucket
  palette that quietly recompiles per request is visible.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Hashable, Optional, Sequence, Tuple


class CompileCache:
    """Thread-safe build-once cache (executables, or anything costly)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[Hashable, Any] = {}
        self._building: Dict[Hashable, threading.Lock] = {}
        self._hits = 0
        self._misses = 0
        self._build_s = 0.0

    def get(self, key: Hashable) -> Optional[Any]:
        with self._lock:
            return self._entries.get(key)

    def get_or_build(self, key: Hashable, build: Callable[[], Any]) -> Any:
        """Return the cached value for ``key``, building it (once, even
        under concurrency) when absent."""
        with self._lock:
            if key in self._entries:
                self._hits += 1
                return self._entries[key]
            gate = self._building.setdefault(key, threading.Lock())
        with gate:
            # double-check: the winner of the race filled the entry
            # while we waited on its gate
            with self._lock:
                if key in self._entries:
                    self._hits += 1
                    return self._entries[key]
            t0 = time.perf_counter()
            value = build()
            dt = time.perf_counter() - t0
            with self._lock:
                self._entries[key] = value
                self._misses += 1
                self._build_s += dt
                self._building.pop(key, None)
            return value

    def __contains__(self, key: Hashable) -> bool:
        with self._lock:
            return key in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._building.clear()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"entries": len(self._entries), "hits": self._hits,
                    "misses": self._misses,
                    "build_s": round(self._build_s, 3)}


# One cache per process: replicas co-located in a worker share compiled
# programs; the driver process gets its own for in-process backends.
DEFAULT_COMPILE_CACHE = CompileCache()


def shape_key(model: str, shape: Sequence[int], dtype: str) -> Tuple:
    """Canonical cache key: ``(model, (dims…), dtype)`` — the
    (model, bucket shape, dtype) triple of the design."""
    return (model, tuple(int(d) for d in shape), str(dtype))


def aot_compile(fn: Callable, arg_specs: Sequence[Tuple[Sequence[int], Any]],
                donate_argnums: Sequence[int] = ()) -> Any:
    """AOT-lower ``fn`` for the given ``(shape, dtype)`` specs and return
    the compiled executable (callable with concrete arrays of exactly
    those shapes). No real data is touched — safe for deploy-time
    warming. ``donate_argnums`` forwards to ``jax.jit`` — the decode
    backends donate their KV pools into the step/prefill programs so
    page writes land IN PLACE instead of copying the whole pool per
    step (at a 768-page pool the functional copy dominated the step;
    donated arguments must not be read after the call — the backends
    swap ``set_pools`` immediately)."""
    import jax
    specs = [jax.ShapeDtypeStruct(tuple(s), d) for s, d in arg_specs]
    return jax.jit(fn, donate_argnums=tuple(donate_argnums)).lower(
        *specs).compile()
