"""Streaming speech recognition behind the C ABI + a Serve backend.

Two integration layers over :class:`tosem_tpu.models.speech.SpeechModel`:

- :class:`CStreamingModel` — registers the JAX streaming functions as the
  callback vtable of ``native/speech_api.cpp`` and drives recognition
  through the C calls (``sp_create_stream`` / ``sp_feed`` /
  ``sp_intermediate`` / ``sp_finish``), the exact surface of the
  reference's ``native_client/deepspeech.h:107-358``.
- :class:`SpeechStreamBackend` — a Serve-lite backend multiplexing many
  C-API streams behind session ids, so HTTP/handle clients can feed audio
  incrementally. Replica loss mid-stream is recovered CLIENT-side by
  replaying buffered audio to a fresh session (:class:`StreamingClient`),
  the way the reference's client retries a broken stream.
"""
from __future__ import annotations

import ctypes
import itertools
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tosem_tpu.serve.backends import CompiledBackendMixin, model_tag

_STREAM_INIT = ctypes.CFUNCTYPE(ctypes.c_void_p, ctypes.c_void_p)
_STREAM_FREE = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_void_p)
_INFER = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
                          ctypes.POINTER(ctypes.c_float), ctypes.c_int32,
                          ctypes.POINTER(ctypes.c_float),
                          ctypes.POINTER(ctypes.c_int32))
_FLUSH = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
                          ctypes.POINTER(ctypes.c_float),
                          ctypes.POINTER(ctypes.c_int32))
# NB: the out buffer must be POINTER(c_char), NOT c_char_p — ctypes hands a
# c_char_p callback arg to Python as an immutable bytes copy, so writes
# through it never reach the C buffer
_DECODE = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                           ctypes.POINTER(ctypes.c_float), ctypes.c_int32,
                           ctypes.POINTER(ctypes.c_char), ctypes.c_int32)


def _bind(lib):
    lib.sp_create_model.restype = ctypes.c_void_p
    lib.sp_create_model.argtypes = [ctypes.c_int32, ctypes.c_int32,
                                    ctypes.c_int32, ctypes.c_int32,
                                    _STREAM_INIT, _STREAM_FREE, _INFER,
                                    _FLUSH, _DECODE, ctypes.c_void_p]
    lib.sp_free_model.argtypes = [ctypes.c_void_p]
    lib.sp_create_stream.restype = ctypes.c_void_p
    lib.sp_create_stream.argtypes = [ctypes.c_void_p]
    lib.sp_free_stream.argtypes = [ctypes.c_void_p]
    lib.sp_feed.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.c_float), ctypes.c_int32]
    lib.sp_intermediate.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int32]
    lib.sp_finish.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_int32]
    lib.sp_stream_frames_emitted.argtypes = [ctypes.c_void_p]
    lib.sp_stream_frames_emitted.restype = ctypes.c_int32
    return lib


def _logsumexp(arr: np.ndarray) -> np.ndarray:
    m = arr.max(axis=-1, keepdims=True)
    return m + np.log(np.exp(arr - m).sum(axis=-1, keepdims=True))


def greedy_ctc_text(logits: np.ndarray, alphabet: str, blank: int) -> str:
    """Greedy CTC collapse (repeat-merge then blank-drop)."""
    ids = logits.argmax(-1)
    out = []
    prev = -1
    for i in ids:
        if i != prev and i != blank:
            out.append(alphabet[i] if i < len(alphabet) else "?")
        prev = i
    return "".join(out)


class CStreamingModel:
    """DeepSpeech-native-client surface over the JAX streaming model."""

    def __init__(self, model, params, alphabet: str,
                 chunk_frames: int = 16):
        import jax
        import jax.numpy as jnp
        from tosem_tpu.native import load_library
        from tosem_tpu.nn.core import variables

        self.model = model
        self.alphabet = alphabet
        cfg = model.cfg
        self.lib = _bind(load_library("speech_api"))
        self._states: Dict[int, Any] = {}
        self._next = itertools.count(1)
        self._lock = threading.Lock()
        vs = variables(params)

        def stream_init(_):
            sid = next(self._next)
            with self._lock:
                self._states[sid] = model.streaming_init(batch=1)
            return sid

        def stream_free(_, sid):
            with self._lock:
                self._states.pop(sid, None)

        def infer(_, sid, frames_p, n_frames, out_p, out_n):
            try:
                x = np.ctypeslib.as_array(
                    frames_p, (n_frames, cfg.n_input)).copy()
                with self._lock:
                    state = self._states[sid]
                logits, state = model.streaming_step(
                    vs, state, jnp.asarray(x[None]))
                with self._lock:
                    self._states[sid] = state
                arr = np.asarray(logits[0], np.float32)
                out = np.ctypeslib.as_array(
                    out_p, (n_frames + cfg.n_context, cfg.n_classes))
                out[:arr.shape[0]] = arr
                out_n[0] = arr.shape[0]
                return 0
            except Exception:
                return -1

        def flush(_, sid, out_p, out_n):
            try:
                with self._lock:
                    state = self._states[sid]
                logits, state = model.streaming_flush(vs, state)
                with self._lock:
                    self._states[sid] = state
                arr = np.asarray(logits[0], np.float32)
                out = np.ctypeslib.as_array(
                    out_p, (cfg.n_context + 1, cfg.n_classes))
                out[:arr.shape[0]] = arr
                out_n[0] = arr.shape[0]
                return 0
            except Exception:
                return -1

        self._scorer = None
        self._beam_width = 16

        def decode(_, logits_p, n_frames, out, cap):
            try:
                arr = np.ctypeslib.as_array(
                    logits_p, (n_frames, cfg.n_classes))
                # refcounted acquire: the lock covers only the pointer
                # grab, not the whole beam search — a concurrent
                # disable/enable defers the native free until the last
                # in-flight decode releases (no use-after-free, no
                # global stall of other streams' infer callbacks)
                scorer = self._acquire_scorer()
                try:
                    if scorer is not None:
                        # DS_EnableExternalScorer path: LM-scored beam
                        from tosem_tpu.data.audio import labels_to_text
                        from tosem_tpu.ops.ctc import beam_search_decode
                        logp = arr - _logsumexp(arr)
                        labels, _ = beam_search_decode(
                            logp, blank=cfg.blank,
                            beam_width=self._beam_width, scorer=scorer)
                        text = labels_to_text(labels, alphabet)
                    else:
                        text = greedy_ctc_text(arr, alphabet, cfg.blank)
                finally:
                    if scorer is not None:
                        self._release_scorer(scorer)
                data = text.encode()[:cap - 1]
                ctypes.memmove(out, data + b"\0", len(data) + 1)
                return 0
            except Exception:
                return -1

        # keep callback objects alive for the model's lifetime
        self._cbs = (_STREAM_INIT(stream_init), _STREAM_FREE(stream_free),
                     _INFER(infer), _FLUSH(flush), _DECODE(decode))
        self._model_p = self.lib.sp_create_model(
            cfg.n_input, cfg.n_classes, chunk_frames, cfg.n_context,
            *self._cbs, None)
        if not self._model_p:
            raise RuntimeError("sp_create_model failed")

    # -- external scorer (DS_EnableExternalScorer:208 parity) --------------

    def _acquire_scorer(self):
        with self._lock:
            sc = self._scorer
            if sc is not None:
                sc._refs = getattr(sc, "_refs", 0) + 1
            return sc

    def _release_scorer(self, sc) -> None:
        with self._lock:
            sc._refs -= 1
            close_now = getattr(sc, "_retired", False) and sc._refs == 0
        if close_now:
            sc.close()

    def _retire(self, sc) -> None:
        """Close a swapped-out scorer once no decode holds it."""
        with self._lock:
            sc._retired = True
            close_now = getattr(sc, "_refs", 0) == 0
        if close_now:
            sc.close()

    def enable_external_scorer(self, path: str, alpha: float = 1.8,
                               beta: float = 0.8,
                               beam_width: int = 16) -> None:
        """Attach an n-gram scorer package (see
        :func:`tosem_tpu.data.scorer.build_scorer`): decodes switch from
        greedy to LM-scored beam search. Word boundaries use THIS
        model's alphabet (not the global default); an alphabet without a
        space gets end-of-utterance scoring only. A package stamped with
        a different alphabet is rejected — mismatched label mappings
        would silently degrade every word to OOV."""
        from tosem_tpu.data.scorer import read_scorer_alphabet
        from tosem_tpu.ops.ctc import Scorer
        stamped = read_scorer_alphabet(path)
        if stamped is not None and stamped != self.alphabet:
            raise ValueError(
                f"scorer package was built with alphabet {stamped!r}, "
                f"model uses {self.alphabet!r}")
        space = (self.alphabet.index(" ") if " " in self.alphabet else -1)
        new = Scorer(path, alpha=alpha, beta=beta, space_index=space)
        # construct first, then swap: a failed load keeps the old scorer
        with self._lock:
            old, self._scorer = self._scorer, new
            self._beam_width = beam_width
        if old is not None:
            self._retire(old)

    def disable_external_scorer(self) -> None:
        with self._lock:
            old, self._scorer = self._scorer, None
        if old is not None:
            self._retire(old)

    # -- the four-call C surface -------------------------------------------
    def create_stream(self) -> int:
        p = self.lib.sp_create_stream(self._model_p)
        if not p:
            raise RuntimeError("sp_create_stream failed")
        return p

    def feed(self, stream: int, frames: np.ndarray) -> None:
        f = np.ascontiguousarray(frames, np.float32)
        rc = self.lib.sp_feed(
            stream, f.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            f.shape[0])
        if rc != 0:
            raise RuntimeError(f"sp_feed rc={rc}")

    def intermediate(self, stream: int, cap: int = 4096) -> str:
        buf = ctypes.create_string_buffer(cap)
        rc = self.lib.sp_intermediate(stream, buf, cap)
        if rc != 0:
            raise RuntimeError(f"sp_intermediate rc={rc}")
        return buf.value.decode()

    def finish(self, stream: int, cap: int = 4096) -> str:
        buf = ctypes.create_string_buffer(cap)
        rc = self.lib.sp_finish(stream, buf, cap)
        self.lib.sp_free_stream(stream)   # free even on failure — no leak
        if rc != 0:
            raise RuntimeError(f"sp_finish rc={rc}")
        return buf.value.decode()

    def abort(self, stream: int) -> None:
        """Free a stream without decoding (failed/abandoned session)."""
        self.lib.sp_free_stream(stream)

    def close(self) -> None:
        self.disable_external_scorer()
        if self._model_p:
            self.lib.sp_free_model(self._model_p)
            self._model_p = None


class SpeechStreamBackend:
    """Serve backend: {op: create|feed|intermediate|finish} session calls."""

    def __init__(self, cfg_name: str = "tiny", seed: int = 0,
                 chunk_frames: int = 8):
        import jax
        from tosem_tpu.models.speech import SpeechConfig, SpeechModel
        cfg = (SpeechConfig.tiny() if cfg_name == "tiny" else SpeechConfig())
        model = SpeechModel(cfg)
        params = model.init(jax.random.PRNGKey(seed))["params"]
        alphabet = "abcdefghijklmnopqrstuvwxyz' -"[:cfg.n_classes - 1]
        self.cm = CStreamingModel(model, params, alphabet,
                                  chunk_frames=chunk_frames)
        self._sessions: Dict[str, int] = {}

    def call(self, request: Dict[str, Any]) -> Any:
        op = request["op"]
        if op == "create":
            sid = request["session"]
            old = self._sessions.pop(sid, None)
            if old is not None:       # client recovery re-creates: free old
                self.cm.abort(old)
            self._sessions[sid] = self.cm.create_stream()
            return {"ok": True}
        stream = self._sessions.get(request["session"])
        if stream is None:
            raise KeyError(f"unknown session {request['session']!r} "
                           "(replica restarted?)")
        if op == "feed":
            self.cm.feed(stream, np.asarray(request["frames"], np.float32))
            return {"ok": True}
        if op == "intermediate":
            return {"text": self.cm.intermediate(stream)}
        if op == "finish":
            # the C stream is freed by finish() even on failure — the
            # session mapping must go with it or the next call would use
            # a dangling pointer
            del self._sessions[request["session"]]
            text = self.cm.finish(stream)
            return {"text": text}
        raise ValueError(f"unknown op {op!r}")


class SpeechBatchBackend(CompiledBackendMixin):
    """Non-streaming utterance transcription behind the micro-batch
    data plane: ``{"frames": [[float, …], …]}`` → ``{"text": str}``.

    Variable-length utterances are bucket-routed by the serve layer and
    zero-padded here to the bucket shape; one AOT-compiled program per
    (max_batch, bucket) runs the whole batch (the LSTM is left-to-right,
    so a request's logits are untouched by its padded tail), then each
    row is sliced back to its true length and greedy-decoded. Batches
    are always padded to ``max_batch`` rows, so batched and sequential
    responses are bit-exact (see :mod:`tosem_tpu.serve.backends`).
    """

    def __init__(self, cfg_name: str = "tiny", seed: int = 0,
                 max_batch: int = 8):
        import jax
        from tosem_tpu.models.speech import SpeechConfig, SpeechModel
        from tosem_tpu.nn.core import variables as _vars
        cfg = (SpeechConfig.tiny() if cfg_name == "tiny" else SpeechConfig())
        self.cfg = cfg
        self.max_batch = max_batch
        self.model = SpeechModel(cfg)
        params = self.model.init(jax.random.PRNGKey(seed))["params"]
        self.alphabet = "abcdefghijklmnopqrstuvwxyz' -"[:cfg.n_classes - 1]
        self._fwd = self.model.logits_fn(_vars(params))
        self._tag = model_tag("speech_logits", cfg, seed)

    @staticmethod
    def length_of(request: Dict[str, Any]) -> int:
        return len(request["frames"])

    def _compiled(self, pad_to: int):
        from tosem_tpu.serve.compile_cache import (DEFAULT_COMPILE_CACHE,
                                                   aot_compile, shape_key)
        key = shape_key(self._tag,
                        (self.max_batch, pad_to, self.cfg.n_input),
                        "float32")
        return DEFAULT_COMPILE_CACHE.get_or_build(
            key, lambda: aot_compile(
                self._fwd,
                [((self.max_batch, pad_to, self.cfg.n_input),
                  np.float32)]))

    def call(self, request: Dict[str, Any]) -> Any:
        return self.call_batch([request])[0]

    def call_batch(self, requests, pad_to: Optional[int] = None):
        from tosem_tpu.models.speech import pad_feats_batch
        if len(requests) > self.max_batch:
            raise ValueError(f"batch of {len(requests)} exceeds "
                             f"max_batch={self.max_batch}")
        frames = [np.asarray(r["frames"], np.float32) for r in requests]
        if pad_to is None:
            pad_to = max(f.shape[0] for f in frames)
        feats, lengths = pad_feats_batch(frames, pad_to,
                                         pad_batch_to=self.max_batch)
        logits = np.asarray(self._compiled(pad_to)(feats), np.float32)
        out = []
        for i in range(len(requests)):
            n = int(lengths[i])
            text = greedy_ctc_text(logits[i, :n], self.alphabet,
                                   self.cfg.blank)
            out.append({"text": text, "frames": n})
        return out

class _SpeechDecodeSeq:
    """Replica-side record of one streaming utterance. ``chunks`` is the
    pre-chunked remaining input; ``rows`` collects emitted logit rows;
    ``outcomes[k]`` memoizes step ``k``'s result (the idempotency ledger
    — see :class:`tosem_tpu.serve.backends._DecodeSeq`)."""

    __slots__ = ("h", "c", "buf", "chunks", "rows", "n_frames",
                 "next_step", "done", "outcomes")

    def __init__(self, h, c, buf, chunks, n_frames: int):
        self.h = h
        self.c = c
        self.buf = buf
        self.chunks = chunks
        self.rows: list = []
        self.n_frames = n_frames
        self.next_step = 0
        self.done = not chunks
        self.outcomes: list = []


class SpeechDecodeBackend(CompiledBackendMixin):
    """Streaming CTC decode behind the iteration-level scheduler — the
    DeepSpeech decode loop as a continuous-batching workload.

    The LSTM carry is the "KV cache" (there are no pages to manage):
    each scheduler step feeds every packed utterance its next
    ``chunk_frames`` frames through ONE compiled
    :meth:`~tosem_tpu.models.speech.SpeechModel.decode_step_fn` program
    with static ``(max_batch, chunk)`` shapes — retired utterances ride
    along as zero rows, so packing never recompiles.

    Bit-exactness with the full forward pass: admission primes the
    context buffer with the pass's own LEFT zero-padding (``c`` zeros)
    plus the first ``c`` real frames, so every window the streamed LSTM
    consumes is a window the full pass consumes, in the same order —
    chunking only re-associates the recurrence, which is exact.

    Implements the decode-client protocol of
    :class:`~tosem_tpu.serve.batching.DecodeQueue` (``admit`` /
    ``step_batch`` / ``result`` / ``release``); no ``spill_seq`` — carry
    state is a few KB per utterance, page pressure does not exist here.
    """

    def __init__(self, cfg_name: str = "tiny", seed: int = 0,
                 max_batch: int = 8, chunk_frames: int = 8,
                 max_frames: int = 512):
        import jax
        from tosem_tpu.models.speech import SpeechConfig, SpeechModel
        from tosem_tpu.nn.core import variables as _vars
        cfg = (SpeechConfig.tiny() if cfg_name == "tiny" else SpeechConfig())
        self.cfg = cfg
        self.max_batch = max_batch
        self.chunk_frames = chunk_frames
        self.max_frames = max_frames
        self.model = SpeechModel(cfg)
        params = self.model.init(jax.random.PRNGKey(seed))["params"]
        self.alphabet = "abcdefghijklmnopqrstuvwxyz' -"[:cfg.n_classes - 1]
        self._step = self.model.decode_step_fn(_vars(params))
        self._seqs: Dict[Any, _SpeechDecodeSeq] = {}
        self._lock = threading.RLock()
        self._tag = model_tag("speech_decode", cfg, seed,
                              chunk=chunk_frames)

    def _step_compiled(self):
        from tosem_tpu.serve.compile_cache import (DEFAULT_COMPILE_CACHE,
                                                   aot_compile, shape_key)
        B, cfg = self.max_batch, self.cfg
        key = shape_key(self._tag + ";step",
                        (B, self.chunk_frames, cfg.n_input), "float32")
        return DEFAULT_COMPILE_CACHE.get_or_build(
            key, lambda: aot_compile(
                self._step,
                [((B, cfg.n_cell), np.float32),
                 ((B, cfg.n_cell), np.float32),
                 ((B, 2 * cfg.n_context, cfg.n_input), np.float32),
                 ((B, self.chunk_frames, cfg.n_input), np.float32)]))

    def warmup(self, shapes: Sequence[Any]) -> Dict[str, Any]:
        from tosem_tpu.serve.compile_cache import DEFAULT_COMPILE_CACHE
        del shapes                   # one step program serves every chunk
        self._step_compiled()
        return {"warmed": 1, "cache": DEFAULT_COMPILE_CACHE.stats()}

    # ------------------------------------------------------- decode client

    def admit(self, seq_id, request: Dict[str, Any]) -> Dict[str, Any]:
        c, cfg = self.cfg.n_context, self.cfg
        with self._lock:
            if seq_id in self._seqs:          # at-least-once replay
                seq = self._seqs[seq_id]
                return {"done": seq.done and seq.next_step == 0}
            frames = np.asarray(request["frames"], np.float32)
            if frames.ndim != 2 or frames.shape[1] != cfg.n_input:
                raise ValueError(f"frames must be [n, {cfg.n_input}], "
                                 f"got {frames.shape}")
            n = frames.shape[0]
            if n < 1:
                raise ValueError("empty frames sequence")
            if n > self.max_frames:
                raise ValueError(f"utterance of {n} frames exceeds "
                                 f"max_frames={self.max_frames}")
            # the full pass pads c zeros each side; stream the padded
            # sequence so every consumed window is a full-pass window
            padded = np.concatenate(
                [np.zeros((c, cfg.n_input), np.float32), frames,
                 np.zeros((c, cfg.n_input), np.float32)], axis=0)
            buf, rest = padded[:2 * c], padded[2 * c:]
            pad = -len(rest) % self.chunk_frames
            if pad:
                rest = np.concatenate(
                    [rest, np.zeros((pad, cfg.n_input), np.float32)])
            chunks = [rest[i:i + self.chunk_frames]
                      for i in range(0, len(rest), self.chunk_frames)]
            zeros = np.zeros((cfg.n_cell,), np.float32)
            self._seqs[seq_id] = _SpeechDecodeSeq(
                h=zeros.copy(), c=zeros.copy(), buf=buf.copy(),
                chunks=chunks, n_frames=n)
            return {"done": self._seqs[seq_id].done}

    def step_batch(self, seq_ids: List[Any],
                   step_idxs: List[int]) -> List[Dict[str, Any]]:
        """One scheduler iteration: feed each live utterance its next
        chunk through the shared static-shape step program."""
        if len(seq_ids) > self.max_batch:
            raise ValueError(f"batch of {len(seq_ids)} exceeds "
                             f"max_batch={self.max_batch}")
        cfg = self.cfg
        with self._lock:
            B = self.max_batch
            h = np.zeros((B, cfg.n_cell), np.float32)
            ch = np.zeros((B, cfg.n_cell), np.float32)
            buf = np.zeros((B, 2 * cfg.n_context, cfg.n_input), np.float32)
            chunk = np.zeros((B, self.chunk_frames, cfg.n_input),
                             np.float32)
            outcomes: List[Optional[Dict[str, Any]]] = []
            live: List[Tuple[int, Any, _SpeechDecodeSeq]] = []
            for row, (sid, step) in enumerate(zip(seq_ids, step_idxs)):
                seq = self._seqs[sid]
                if step < seq.next_step:      # replayed step: memo only
                    outcomes.append(seq.outcomes[step])
                    continue
                if step > seq.next_step:
                    raise RuntimeError(
                        f"step {step} for {sid!r} skips ahead of "
                        f"{seq.next_step} (scheduler bug)")
                if seq.done:
                    outcomes.append({"done": True})
                    continue
                h[row], ch[row], buf[row] = seq.h, seq.c, seq.buf
                chunk[row] = seq.chunks[seq.next_step]
                outcomes.append(None)
                live.append((row, sid, seq))
            if live:
                logits, h2, c2, buf2 = self._step_compiled()(h, ch, buf,
                                                             chunk)
                logits = np.asarray(logits, np.float32)
                h2, c2 = np.asarray(h2), np.asarray(c2)
                buf2 = np.asarray(buf2)
                for row, sid, seq in live:
                    seq.h, seq.c = h2[row], c2[row]
                    seq.buf = buf2[row]
                    seq.rows.append(logits[row])
                    seq.next_step += 1
                    out = {"done": seq.next_step >= len(seq.chunks),
                           "frames": self.chunk_frames}
                    seq.done = out["done"]
                    if seq.done:
                        # final payload rides the outcome (zero extra
                        # round trips to retire — see BertDecodeBackend)
                        out["result"] = self._result_locked(seq)
                    seq.outcomes.append(out)
                    outcomes[row] = out
            return outcomes

    def _result_locked(self, seq: _SpeechDecodeSeq) -> Dict[str, Any]:
        rows = (np.concatenate(seq.rows)[:seq.n_frames]
                if seq.rows else
                np.zeros((0, self.cfg.n_classes), np.float32))
        text = greedy_ctc_text(rows, self.alphabet, self.cfg.blank)
        return {"text": text, "frames": seq.n_frames}

    def result(self, seq_id) -> Dict[str, Any]:
        with self._lock:
            return self._result_locked(self._seqs[seq_id])

    def release(self, seq_id) -> None:
        with self._lock:
            self._seqs.pop(seq_id, None)

    def stats(self) -> Dict[str, Any]:
        out = super().stats()
        with self._lock:
            out["decode_sequences"] = len(self._seqs)
        return out


class StreamingClient:
    """Client-side stream with replay recovery (broken-stream retry).

    Pins a session to whichever replica answers; if the replica dies
    mid-stream (KeyError/ActorDiedError surfaces through the handle), the
    client re-creates the session and replays every buffered chunk — the
    stream survives replica loss at the cost of recomputation.
    """

    def __init__(self, handle, session: str):
        self.handle = handle
        self.session = session
        self._fed: list = []
        self._call({"op": "create", "session": session})

    def _call(self, req, retried: bool = False):
        try:
            return self.handle.call(req, timeout=60.0)
        except Exception:
            if retried:
                raise
            # replica lost: fresh session, replay every ACKNOWLEDGED chunk
            # (the in-flight request is NOT in _fed yet — replay-then-retry
            # applies it exactly once in the new session; whatever the dead
            # replica partially applied died with its session)
            self.handle.call({"op": "create", "session": self.session},
                             timeout=60.0)
            for frames in self._fed:
                self.handle.call({"op": "feed", "session": self.session,
                                  "frames": frames}, timeout=60.0)
            if req["op"] == "create":
                return {"ok": True}
            return self._call(req, retried=True)

    def feed(self, frames) -> None:
        frames = np.asarray(frames, np.float32).tolist()
        self._call({"op": "feed", "session": self.session,
                    "frames": frames})
        self._fed.append(frames)   # buffer only after the ack

    def intermediate(self) -> str:
        return self._call({"op": "intermediate",
                           "session": self.session})["text"]

    def finish(self) -> str:
        return self._call({"op": "finish", "session": self.session})["text"]
