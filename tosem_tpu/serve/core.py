"""Serve-lite: deployments, replica routing, retries — on the actor runtime.

The reference's serving stack (SURVEY §2.1 Ray Serve) is a controller that
deploys backend classes as replica actors, a router that load-balances
requests over them, and an HTTP proxy in front
(``python/ray/serve/api.py:36,210,361``; ``serve/router.py``;
``serve/backend_worker.py``). This is the same architecture on
:mod:`tosem_tpu.runtime`: replicas are runtime actors with restart policies,
the router is driver-side (single-controller — no distributed router actors
needed), and failures re-dispatch to surviving replicas.

    serve = Serve()
    serve.deploy("echo", EchoBackend, num_replicas=2)
    h = serve.get_handle("echo")
    fut = h.remote({"x": 1})
    fut.result(timeout=5)

Backend contract: a class whose ``call(self, request)`` handles one request
(the ``__call__`` of a Serve backend).
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import tosem_tpu.runtime as rt
from tosem_tpu.runtime.common import (ActorDiedError, TaskCancelledError,
                                      WorkerCrashedError)

RETRYABLE = (ActorDiedError, WorkerCrashedError)


class ServeFuture:
    """A routed request: retries on replica death, like the reference's
    router re-submitting to another worker replica."""

    def __init__(self, deployment: "Deployment", request: Any,
                 max_retries: int, pin: Optional[int] = None):
        self._dep = deployment
        self._request = request
        self._retries_left = max_retries
        self._pin = pin
        self._ref = deployment._dispatch(request, pin=pin)

    def result(self, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = (None if deadline is None
                         else max(deadline - time.monotonic(), 0.001))
            try:
                return rt.get(self._ref, timeout=remaining)
            except RETRYABLE:
                if self._retries_left <= 0:
                    raise
                self._retries_left -= 1
                self._ref = self._dep._dispatch(self._request, pin=self._pin)


class Deployment:
    """One named backend: N replica actors + a round-robin pointer."""

    def __init__(self, name: str, backend_cls, num_replicas: int,
                 init_args: Tuple, init_kwargs: Dict,
                 max_restarts: int, max_retries: int):
        self.name = name
        self.backend_cls = backend_cls
        self.max_retries = max_retries
        self._init_args = init_args
        self._init_kwargs = init_kwargs
        self._actor_cls = rt.remote(max_restarts=max_restarts)(backend_cls)
        self._lock = threading.Lock()
        self._replicas: List[Any] = [
            self._actor_cls.remote(*init_args, **init_kwargs)
            for _ in range(num_replicas)]
        self._rr = itertools.count()
        self._closed = False
        # (ref, replica) pairs not yet observed done — drives both the
        # least-loaded dispatch and the autoscaler's demand signal.
        # Pruned on every dispatch and load() call, so counts are true
        # in-flight numbers and results never stay pinned.
        self._outstanding: List[Any] = []

    def _counts_locked(self) -> Dict[int, int]:
        """Per-replica outstanding counts from the current (possibly
        slightly stale) list. Caller holds self._lock."""
        counts: Dict[int, int] = {id(r): 0 for r in self._replicas}
        for _, rep in self._outstanding:
            if id(rep) in counts:
                counts[id(rep)] += 1
        return counts

    def _prune_amortized(self) -> None:
        """Bound both count staleness and pinned-result growth without an
        O(outstanding) rt.wait on every request: prune once the list
        exceeds a few requests per replica."""
        with self._lock:
            threshold = max(32, 4 * len(self._replicas))
            needs = len(self._outstanding) > threshold
        if needs:
            self.load()

    def _dispatch(self, request: Any, pin: Optional[int] = None):
        self._prune_amortized()
        with self._lock:
            replicas = list(self._replicas)
            if not replicas:
                # deleted deployment: a clear terminal signal, not a
                # min()-of-empty / mod-zero crash inside a retry loop
                raise ActorDiedError(
                    f"deployment {self.name!r} has no replicas "
                    "(deleted?)")
            if pin is None:
                # least-loaded with round-robin tiebreak: fresh replicas
                # absorb new traffic. Counts may include a few completed
                # -but-unpruned refs (bounded by _prune_amortized), which
                # only biases toward spreading. NOTE: already-submitted
                # calls stay with their replica (actor queues preserve
                # stateful ordering) — scale-up helps future requests.
                counts = self._counts_locked()
                order = next(self._rr)
                i = min(range(len(replicas)),
                        key=lambda j: (counts.get(id(replicas[j]), 0),
                                       (j - order) % len(replicas)))
            else:
                i = pin % len(replicas)
            replica = replicas[i]
        ref = replica.call.remote(request)
        with self._lock:
            self._outstanding.append((ref, replica))
        return ref

    @property
    def num_replicas(self) -> int:
        with self._lock:
            return len(self._replicas)

    def load(self) -> int:
        """In-flight request count (the autoscaler's demand signal, the
        replica queue-length metric Serve's controller scrapes). Prunes
        refs that completed since the last call."""
        with self._lock:
            pairs = list(self._outstanding)
        if not pairs:
            return 0
        refs = [r for r, _ in pairs]
        done, _ = rt.wait(refs, num_returns=len(refs), timeout=0.0)
        done_set = set(done)
        with self._lock:
            self._outstanding = [(r, rep) for r, rep in self._outstanding
                                 if r not in done_set]
            return len(self._outstanding)

    def handle(self, pin: Optional[int] = None) -> "Handle":
        """``pin``: route every request of this handle to one replica —
        session affinity for stateful backends (streaming)."""
        return Handle(self, pin=pin)

    def scale(self, num_replicas: int) -> None:
        """Add/remove replicas (the controller's autoscale entry point).

        Scale-down retires the LEAST-LOADED replicas (ideally idle ones)
        rather than a fixed tail — killing a mid-request replica forces
        client-visible retries. No-op after delete() (a late autoscaler
        tick must not spawn unreachable actors).
        """
        if num_replicas < 1:
            raise ValueError("a deployment needs at least one replica; "
                             "use Serve.delete to tear it down")
        self.load()              # prune so counts below are near-exact
        with self._lock:
            if self._closed:
                return
            cur = len(self._replicas)
            if num_replicas > cur:
                self._replicas.extend(
                    self._actor_cls.remote(*self._init_args,
                                           **self._init_kwargs)
                    for _ in range(num_replicas - cur))
            elif num_replicas < cur:
                # counts computed UNDER the lock: a dispatch racing this
                # scale-down either lands before (counted, replica looks
                # busy and survives) or after (sees the shrunken list)
                counts = self._counts_locked()
                victims = sorted(self._replicas,
                                 key=lambda r: counts.get(id(r), 0))[
                                     :cur - num_replicas]
                victim_ids = {id(v) for v in victims}
                self._replicas = [r for r in self._replicas
                                  if id(r) not in victim_ids]
                for v in victims:
                    rt.kill(v)

    def close(self) -> None:
        """Kill every replica and refuse further scaling (delete path)."""
        with self._lock:
            self._closed = True
            victims = list(self._replicas)
            self._replicas = []
        for v in victims:
            rt.kill(v)


class Handle:
    """Client-side handle (``serve.get_handle`` role)."""

    def __init__(self, deployment: Deployment, pin: Optional[int] = None):
        self._dep = deployment
        self._pin = pin

    def remote(self, request: Any) -> ServeFuture:
        return ServeFuture(self._dep, request, self._dep.max_retries,
                           pin=self._pin)

    def call(self, request: Any, timeout: Optional[float] = None) -> Any:
        return self.remote(request).result(timeout)


class Serve:
    """The controller: name → deployment registry (serve/api.py:36 role)."""

    def __init__(self):
        if not rt.is_initialized():
            rt.init()
        self._deployments: Dict[str, Deployment] = {}
        self._lock = threading.Lock()

    def deploy(self, name: str, backend_cls, *, num_replicas: int = 1,
               init_args: Tuple = (), init_kwargs: Optional[Dict] = None,
               max_restarts: int = 2, max_retries: int = 3) -> Deployment:
        with self._lock:
            if name in self._deployments:
                raise ValueError(f"deployment {name!r} already exists")
            dep = Deployment(name, backend_cls, num_replicas, init_args,
                             init_kwargs or {}, max_restarts, max_retries)
            self._deployments[name] = dep
            return dep

    def get_handle(self, name: str) -> Handle:
        return self._deployments[name].handle()

    def delete(self, name: str) -> None:
        with self._lock:
            dep = self._deployments.pop(name, None)
        if dep is not None:
            dep.close()          # marks closed: late scale() calls no-op

    def list_deployments(self) -> List[str]:
        with self._lock:
            return sorted(self._deployments)

    def get_deployment(self, name: str) -> Optional[Deployment]:
        """Public registry accessor (autoscaler/dashboard use this, not
        the private dict)."""
        with self._lock:
            return self._deployments.get(name)

    def deployments(self) -> Dict[str, Deployment]:
        with self._lock:
            return dict(self._deployments)
