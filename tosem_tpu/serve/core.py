"""Serve-lite: deployments, replica routing, retries — on the actor runtime.

The reference's serving stack (SURVEY §2.1 Ray Serve) is a controller that
deploys backend classes as replica actors, a router that load-balances
requests over them, and an HTTP proxy in front
(``python/ray/serve/api.py:36,210,361``; ``serve/router.py``;
``serve/backend_worker.py``). This is the same architecture on
:mod:`tosem_tpu.runtime`: replicas are runtime actors with restart policies,
the router is driver-side (single-controller — no distributed router actors
needed), and failures re-dispatch to surviving replicas.

    serve = Serve()
    serve.deploy("echo", EchoBackend, num_replicas=2)
    h = serve.get_handle("echo")
    fut = h.remote({"x": 1})
    fut.result(timeout=5)

Backend contract: a class whose ``call(self, request)`` handles one request
(the ``__call__`` of a Serve backend).
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import (Any, Callable, Dict, List, Optional, Sequence, Tuple,
                    Union)

import tosem_tpu.runtime as rt
from tosem_tpu.chaos import hooks as _chaos
from tosem_tpu.runtime.common import (ActorDiedError, TaskCancelledError,
                                      TaskError, WorkerCrashedError)
from tosem_tpu.serve.batching import (BatchingReplica, BatchPolicy,
                                      BatchQueue, DecodePolicy,
                                      DecodeQueue)
from tosem_tpu.serve.breaker import CircuitBreaker, CircuitOpen

RETRYABLE = (ActorDiedError, WorkerCrashedError)


class ServeFuture:
    """A routed request: retries on replica death with exponential
    backoff, like the reference's router re-submitting to another worker
    replica — but with a bounded retry budget so a dead deployment fails
    in bounded time instead of spinning."""

    def __init__(self, deployment: "Deployment", request: Any,
                 max_retries: int, pin: Optional[int] = None):
        self._dep = deployment
        self._request = request
        self._retries_left = max_retries
        self._attempt = 0
        self._pin = pin
        # breaker admission happens per attempt, per request, so probe
        # ownership is this future's alone — a stale request finishing
        # late can never free or fail another request's probe
        self._probe = False
        self._ref = self._dispatch_attempt()

    def _dispatch_attempt(self):
        """Admit through the breaker, then dispatch — releasing an
        acquired probe slot if the dispatch itself fails (a deleted
        deployment raising here must not wedge the breaker in
        'probe in flight' forever)."""
        breaker = self._dep.breaker
        self._probe = breaker.allow() if breaker is not None else False
        try:
            return self._dep._dispatch(self._request, pin=self._pin)
        except BaseException:
            if breaker is not None and self._probe:
                breaker.release_probe()
                self._probe = False
            raise

    def result(self, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        breaker = self._dep.breaker
        while True:
            remaining = (None if deadline is None
                         else max(deadline - time.monotonic(), 0.001))
            try:
                value = rt.get(self._ref, timeout=remaining)
            except RETRYABLE:
                if breaker is not None:
                    breaker.record_failure(probe=self._probe)
                    self._probe = False
                if self._retries_left <= 0:
                    raise
                # deterministic exponential backoff: replica restarts /
                # re-deploys get breathing room before the re-dispatch —
                # clipped to the caller's own deadline (never sleep past
                # the time budget of a result(timeout=...) call)
                delay = min(self._dep.backoff_base_s * (2 ** self._attempt),
                            self._dep.backoff_cap_s)
                if deadline is not None:
                    budget = deadline - time.monotonic()
                    if budget <= 0:
                        raise          # out of time: surface the failure
                    # at most half the remaining budget goes to backing
                    # off — sleeping the WHOLE budget would guarantee
                    # the retried attempt times out unwaited
                    delay = min(delay, budget / 2)
                self._retries_left -= 1
                time.sleep(delay)
                self._attempt += 1
                self._ref = self._dispatch_attempt()  # may raise CircuitOpen
            except TaskError:
                # application error: counts against the breaker (the
                # backend is failing requests) but is never retried —
                # the caller sees its own exception
                if breaker is not None:
                    breaker.record_failure(probe=self._probe)
                    self._probe = False
                raise
            except BaseException:
                # anything without a clear verdict — the caller's wait
                # timed out (the request may still land later),
                # cancellation, a result that fails to unpickle,
                # KeyboardInterrupt: free our probe slot rather than
                # wedging the breaker in 'probe in flight' forever
                if breaker is not None and self._probe:
                    breaker.release_probe()
                    self._probe = False
                raise
            else:
                if breaker is not None:
                    breaker.record_success(probe=self._probe)
                    self._probe = False
                return value


class Deployment:
    """One named backend: N replica actors + a round-robin pointer."""

    def __init__(self, name: str, backend_cls, num_replicas: int,
                 init_args: Tuple, init_kwargs: Dict,
                 max_restarts: int, max_retries: int,
                 breaker: Optional[CircuitBreaker] = None,
                 backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 2.0,
                 batch_policy: Optional[BatchPolicy] = None,
                 decode_policy: Optional[DecodePolicy] = None,
                 warmup_shapes: Optional[Sequence] = None):
        if batch_policy is not None and decode_policy is not None:
            raise ValueError("a deployment is either micro-batched "
                             "(batch_policy) or continuous-batching "
                             "decode (decode_policy), not both")
        if decode_policy is not None:
            # best-effort deploy-time guard: max_active beyond the
            # backend's static batch dimension would fail every packed
            # sequence at the first oversized step_batch
            backend_max = (init_kwargs or {}).get(
                "max_batch", getattr(backend_cls, "max_batch", None))
            if (isinstance(backend_max, int)
                    and decode_policy.max_active > backend_max):
                raise ValueError(
                    f"decode_policy.max_active={decode_policy.max_active}"
                    f" exceeds the backend's max_batch={backend_max} "
                    "(the compiled step program's batch dimension)")
        self.name = name
        self.backend_cls = backend_cls
        self.max_retries = max_retries
        self.breaker = breaker
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._init_args = init_args
        self._init_kwargs = init_kwargs
        self.batch_policy = batch_policy
        self.decode_policy = decode_policy
        self._warmup_shapes = list(warmup_shapes or [])
        if batch_policy is not None:
            # batched deployments run behind the replica wrapper: it
            # owns the (status, value)-per-request wire and per-request
            # error isolation, so one poison request can never fail its
            # batchmates (see serve/batching.py)
            self._actor_cls = rt.remote(max_restarts=max_restarts)(
                BatchingReplica)
            self._spawn = lambda: self._actor_cls.remote(
                backend_cls, init_args, init_kwargs or {})
        else:
            self._actor_cls = rt.remote(max_restarts=max_restarts)(
                backend_cls)
            self._spawn = lambda: self._actor_cls.remote(
                *init_args, **(init_kwargs or {}))
        self._lock = threading.Lock()
        self._replicas: List[Any] = [self._spawn()
                                     for _ in range(num_replicas)]
        self._rr = itertools.count()
        self._closed = False
        # (ref, replica, n_logical) triples not yet observed done —
        # drives both the least-loaded dispatch and the autoscaler's
        # demand signal. n_logical is the LOGICAL request count behind
        # a dispatch (a 16-request micro-batch weighs 16, not 1), so
        # routing and scaling see requests, never dispatches. Pruned on
        # every dispatch and load() call, so counts are true in-flight
        # numbers and results never stay pinned.
        self._outstanding: List[Any] = []
        # the two data planes share the queue slot: Handle routing,
        # load() accounting, stats(), and close() treat them uniformly
        # (both expose submit/depth/stats/close)
        if batch_policy is not None:
            self._queue: Optional[Any] = BatchQueue(self, batch_policy)
        elif decode_policy is not None:
            self._queue = DecodeQueue(self, decode_policy)
        else:
            self._queue = None
        if self._warmup_shapes:
            self.warmup(self._warmup_shapes)

    def _counts_locked(self) -> Dict[int, int]:
        """Per-replica outstanding LOGICAL request counts from the
        current (possibly slightly stale) list. Caller holds
        self._lock."""
        counts: Dict[int, int] = {id(r): 0 for r in self._replicas}
        for _, rep, n in self._outstanding:
            if id(rep) in counts:
                counts[id(rep)] += n
        return counts

    def _prune_amortized(self) -> None:
        """Bound both count staleness and pinned-result growth without an
        O(outstanding) rt.wait on every request: prune once the list
        exceeds a few requests per replica."""
        with self._lock:
            threshold = max(32, 4 * len(self._replicas))
            needs = len(self._outstanding) > threshold
        if needs:
            self.load()

    def _pick_replica(self, pin: Optional[int]) -> Tuple[Any, int]:
        """Least-loaded routing over LOGICAL request counts (shared by
        the single-request and micro-batch dispatch paths)."""
        with self._lock:
            replicas = list(self._replicas)
            if not replicas:
                # deleted deployment: a clear terminal signal, not a
                # min()-of-empty / mod-zero crash inside a retry loop
                raise ActorDiedError(
                    f"deployment {self.name!r} has no replicas "
                    "(deleted?)")
            if pin is None:
                # least-loaded with round-robin tiebreak: fresh replicas
                # absorb new traffic. Counts may include a few completed
                # -but-unpruned refs (bounded by _prune_amortized), which
                # only biases toward spreading. NOTE: already-submitted
                # calls stay with their replica (actor queues preserve
                # stateful ordering) — scale-up helps future requests.
                counts = self._counts_locked()
                order = next(self._rr)
                i = min(range(len(replicas)),
                        key=lambda j: (counts.get(id(replicas[j]), 0),
                                       (j - order) % len(replicas)))
            else:
                i = pin % len(replicas)
            return replicas[i], i

    def _fire_chaos(self, replica, i: int) -> None:
        act = _chaos.fire("serve.dispatch", target=self.name, replica=i)
        if act is not None:
            if act["action"] == "crash_replica":
                # chaos: SIGKILL the replica's process just before the
                # request lands — the call fails with ActorDiedError,
                # the restart policy replays the replica's init, and
                # the router's retry path re-dispatches
                from tosem_tpu.chaos.injector import crash_actor_process
                crash_actor_process(replica._actor_id)
            elif act["action"] == "slow_replica":
                time.sleep(act["delay_s"])

    def _dispatch(self, request: Any, pin: Optional[int] = None):
        # breaker admission is the caller's job (ServeFuture): it owns
        # the per-request probe flag the breaker hands out
        self._prune_amortized()
        replica, i = self._pick_replica(pin)
        self._fire_chaos(replica, i)
        ref = replica.call.remote(request)
        with self._lock:
            self._outstanding.append((ref, replica, 1))
        return ref

    def _dispatch_batch(self, requests: List[Any],
                        bucket: Optional[int] = None,
                        pin: Optional[int] = None):
        """Ship one micro-batch to a replica (the BatchQueue's dispatch
        path). ``bucket`` is the padding target the batch was binned
        under; the replica pads every request to exactly that shape, so
        the compiled-program cache sees one program per bucket. Returns
        ``(ref, replica)`` so the completion thread can retry elsewhere
        on replica death. In-flight accounting weighs the batch by its
        LOGICAL size."""
        self._prune_amortized()
        replica, i = self._pick_replica(pin)
        self._fire_chaos(replica, i)
        ref = replica.call_batch.remote(requests, bucket)
        with self._lock:
            self._outstanding.append((ref, replica, len(requests)))
        return ref, replica

    @property
    def num_replicas(self) -> int:
        with self._lock:
            return len(self._replicas)

    def load(self) -> int:
        """In-flight LOGICAL request count plus micro-batch queue depth
        (the autoscaler's demand signal, the replica queue-length metric
        Serve's controller scrapes). Queued-but-undispatched requests
        count too: demand waiting for a batch slot is exactly what
        scale-up should relieve — and a 16-request batch in flight is 16
        units of demand, not one dispatch. Prunes refs that completed
        since the last call."""
        queued = self._queue.depth() if self._queue is not None else 0
        with self._lock:
            triples = list(self._outstanding)
        if not triples:
            return queued
        refs = [r for r, _, _ in triples]
        done, _ = rt.wait(refs, num_returns=len(refs), timeout=0.0)
        done_set = set(done)
        with self._lock:
            self._outstanding = [t for t in self._outstanding
                                 if t[0] not in done_set]
            return queued + sum(n for _, _, n in self._outstanding)

    def handle(self, pin: Optional[int] = None) -> "Handle":
        """``pin``: route every request of this handle to one replica —
        session affinity for stateful backends (streaming)."""
        return Handle(self, pin=pin)

    def scale(self, num_replicas: int) -> None:
        """Add/remove replicas (the controller's autoscale entry point).

        Scale-down retires the LEAST-LOADED replicas (ideally idle ones)
        rather than a fixed tail — killing a mid-request replica forces
        client-visible retries. No-op after delete() (a late autoscaler
        tick must not spawn unreachable actors).
        """
        if num_replicas < 1:
            raise ValueError("a deployment needs at least one replica; "
                             "use Serve.delete to tear it down")
        self.load()              # prune so counts below are near-exact
        with self._lock:
            if self._closed:
                return
            cur = len(self._replicas)
            if num_replicas > cur:
                fresh = [self._spawn() for _ in range(num_replicas - cur)]
                self._replicas.extend(fresh)
                # pre-warm new replicas without blocking the scaler:
                # the warmup call queues FIRST on the fresh actor, so
                # any request routed there waits behind the compile
                # instead of paying it (actor queues are FIFO)
                for r in fresh:
                    self._warm_async(r)
            elif num_replicas < cur:
                # counts computed UNDER the lock: a dispatch racing this
                # scale-down either lands before (counted, replica looks
                # busy and survives) or after (sees the shrunken list).
                # Decode steps bypass _dispatch, so fold in the decode
                # queue's own per-replica sequence counts — killing a
                # replica packing live sequences forces a full re-decode
                # of each one (and a breaker trip per logical sequence)
                counts = self._counts_locked()
                if self.decode_policy is not None and \
                        self._queue is not None:
                    for key, n in self._queue.replica_loads().items():
                        counts[key] = counts.get(key, 0) + n
                victims = sorted(self._replicas,
                                 key=lambda r: counts.get(id(r), 0))[
                                     :cur - num_replicas]
                victim_ids = {id(v) for v in victims}
                self._replicas = [r for r in self._replicas
                                  if id(r) not in victim_ids]
                for v in victims:
                    rt.kill(v)

    def _can_warm(self) -> bool:
        return (self.batch_policy is not None
                or hasattr(self.backend_cls, "warmup"))

    def _warm_async(self, replica) -> None:
        if self._warmup_shapes and self._can_warm():
            replica.warmup.remote(self._warmup_shapes)

    def warmup(self, shapes: Sequence, timeout: Optional[float] = None
               ) -> List[Any]:
        """Pre-compile the declared shapes on EVERY replica and block
        until done — the deploy-time warm-cache fill that keeps replica
        0's first request from eating a multi-second JIT. ``shapes`` is
        backend-defined (the model backends take their bucket palette).
        Requires a backend with a ``warmup(shapes)`` method (batched
        deployments always have one via the replica wrapper)."""
        if not self._can_warm():
            raise ValueError(
                f"backend {self.backend_cls.__name__} has no warmup() "
                "and the deployment is unbatched — nothing to pre-warm")
        with self._lock:
            replicas = list(self._replicas)
        refs = [r.warmup.remote(list(shapes)) for r in replicas]
        return [rt.get(ref, timeout=timeout) for ref in refs]

    def stats(self) -> Dict[str, Any]:
        """Data-plane snapshot: replica count, logical load, and (for
        batched deployments) queue depth / batch-size telemetry — the
        ``/-/stats`` ingress payload."""
        out: Dict[str, Any] = {"replicas": self.num_replicas,
                               "load": self.load(),
                               "batched": self.batch_policy is not None,
                               "decode": self.decode_policy is not None}
        if self._queue is not None:
            out.update(self._queue.stats())
            if self.batch_policy is not None:
                out["max_batch_size"] = self.batch_policy.max_batch_size
                out["batch_wait_ms"] = self.batch_policy.batch_wait_ms
            else:
                out["max_active"] = self.decode_policy.max_active
        return out

    def close(self) -> None:
        """Kill every replica and refuse further scaling (delete path).
        Queued-but-undispatched requests fail with ActorDiedError."""
        with self._lock:
            self._closed = True
            victims = list(self._replicas)
            self._replicas = []
        if self._queue is not None:
            self._queue.close()
        for v in victims:
            rt.kill(v)


class Handle:
    """Client-side handle (``serve.get_handle`` role).

    On a batched deployment, un-pinned requests ride the micro-batch
    queue (a :class:`~tosem_tpu.serve.batching.BatchedFuture` comes
    back); pinned handles bypass batching — session affinity implies
    stateful per-session ordering that must not interleave with other
    sessions' requests inside one batch."""

    def __init__(self, deployment: Deployment, pin: Optional[int] = None):
        self._dep = deployment
        self._pin = pin

    def _submit_batched(self, request: Any, sync: bool,
                        timeout: Optional[float] = None):
        """Breaker-admitted submit to the micro-batch queue: admission
        happens HERE (not at flush) so an open circuit rejects at
        ``.remote()`` exactly like the unbatched path — per logical
        request, owning its own probe flag. A submit that raises (queue
        closed by delete) releases an acquired probe rather than
        wedging the breaker in 'probe in flight' forever (mirror of
        ``ServeFuture._dispatch_attempt``)."""
        dep = self._dep
        breaker = dep.breaker
        probe = breaker.allow() if breaker is not None else False
        try:
            return dep._queue.submit(request, probe=probe, sync=sync,
                                     timeout=timeout)
        except BaseException:
            if breaker is not None and probe:
                breaker.release_probe()
            raise

    def remote(self, request: Any):
        dep = self._dep
        if dep._queue is not None and self._pin is None:
            return self._submit_batched(request, sync=False)
        return ServeFuture(dep, request, dep.max_retries, pin=self._pin)

    def call(self, request: Any, timeout: Optional[float] = None) -> Any:
        dep = self._dep
        if dep._queue is not None and self._pin is None:
            # sync + idle queue: submit completes the request inline on
            # this thread (no completion-thread spawn / Event handoff),
            # keeping single-client p50 at the unbatched path's cost
            return self._submit_batched(request, sync=True,
                                        timeout=timeout).result(timeout)
        return self.remote(request).result(timeout)

    def stream(self, request: Any, on_token,
               timeout: Optional[float] = None) -> Any:
        """Streaming decode: ``on_token(tokens, done)`` fires from the
        decode scheduler as tokens commit (it must be fast and non-
        blocking — push into a queue, never write a slow socket
        directly); returns the final result like :meth:`call`. Only a
        continuous-batching (DecodeQueue) deployment streams."""
        from tosem_tpu.serve.batching import DecodeQueue
        dep = self._dep
        if not isinstance(dep._queue, DecodeQueue) or self._pin is not None:
            raise TypeError(
                f"deployment {dep.name!r} has no decode queue to "
                "stream from (deploy with decode_policy=...)")
        breaker = dep.breaker
        probe = breaker.allow() if breaker is not None else False
        try:
            fut = dep._queue.submit(request, probe=probe,
                                    on_token=on_token)
        except BaseException:
            if breaker is not None and probe:
                breaker.release_probe()
            raise
        return fut.result(timeout)


class Serve:
    """The controller: name → deployment registry (serve/api.py:36 role)."""

    def __init__(self):
        if not rt.is_initialized():
            rt.init()
        self._deployments: Dict[str, Deployment] = {}
        self._lock = threading.Lock()

    def deploy(self, name: str, backend_cls, *, num_replicas: int = 1,
               init_args: Tuple = (), init_kwargs: Optional[Dict] = None,
               max_restarts: int = 2, max_retries: int = 3,
               circuit_breaker: Union[bool, CircuitBreaker, None] = None,
               backoff_base_s: float = 0.05,
               backoff_cap_s: float = 2.0,
               max_batch_size: int = 1,
               batch_wait_ms: float = 5.0,
               buckets: Optional[Sequence[int]] = None,
               length_of: Optional[Callable[[Any], int]] = None,
               batch_policy: Optional[BatchPolicy] = None,
               decode_policy: Optional[DecodePolicy] = None,
               warmup_shapes: Optional[Sequence] = None) -> Deployment:
        """``circuit_breaker``: True for a default breaker (5 consecutive
        failures open it for 5s), or a configured
        :class:`~tosem_tpu.serve.breaker.CircuitBreaker`; None disables
        (the pre-breaker behavior).

        ``max_batch_size > 1`` (or an explicit ``batch_policy``) turns
        on the adaptive micro-batching data plane: concurrent requests
        coalesce into batches under the ``batch_wait_ms`` latency
        budget, optionally binned into padding ``buckets`` via
        ``length_of`` (see :mod:`tosem_tpu.serve.batching`).
        ``warmup_shapes`` pre-compiles the declared shapes on every
        replica before ``deploy`` returns, so the first request never
        pays the JIT.

        ``decode_policy`` turns on the iteration-level decode data plane
        instead (continuous batching for autoregressive backends — see
        :class:`~tosem_tpu.serve.batching.DecodeQueue`): the backend
        must implement the decode-client protocol (``admit`` /
        ``step_batch`` / ``result`` / ``release``). Mutually exclusive
        with micro-batching."""
        if circuit_breaker is True:
            breaker: Optional[CircuitBreaker] = CircuitBreaker()
        elif isinstance(circuit_breaker, CircuitBreaker):
            breaker = circuit_breaker
        else:
            breaker = None
        if batch_policy is None and max_batch_size > 1:
            batch_policy = BatchPolicy(max_batch_size=max_batch_size,
                                       batch_wait_ms=batch_wait_ms,
                                       buckets=buckets,
                                       length_of=length_of)
        # reserve the name, then construct OUTSIDE the registry lock:
        # Deployment.__init__ blocks on warmup_shapes compiles (multi-
        # second on model backends), and holding the global lock through
        # that would stall every concurrent deploy/get_handle/stats call
        with self._lock:
            if name in self._deployments:
                raise ValueError(f"deployment {name!r} already exists")
            self._deployments[name] = None       # reservation marker
        try:
            dep = Deployment(name, backend_cls, num_replicas, init_args,
                             init_kwargs or {}, max_restarts, max_retries,
                             breaker=breaker, backoff_base_s=backoff_base_s,
                             backoff_cap_s=backoff_cap_s,
                             batch_policy=batch_policy,
                             decode_policy=decode_policy,
                             warmup_shapes=warmup_shapes)
        except BaseException:
            with self._lock:
                self._deployments.pop(name, None)
            raise
        with self._lock:
            if name not in self._deployments:
                deleted = True   # delete() raced the warmup
            else:
                self._deployments[name] = dep
                deleted = False
        if deleted:
            dep.close()
            raise RuntimeError(
                f"deployment {name!r} was deleted while deploying")
        return dep

    def get_handle(self, name: str) -> Handle:
        with self._lock:
            dep = self._deployments[name]
        if dep is None:
            raise KeyError(f"deployment {name!r} is still deploying "
                           "(warmup in progress)")
        return dep.handle()

    def delete(self, name: str) -> None:
        with self._lock:
            dep = self._deployments.pop(name, None)
        if dep is not None:
            dep.close()          # marks closed: late scale() calls no-op

    def list_deployments(self) -> List[str]:
        with self._lock:
            return sorted(self._deployments)

    def get_deployment(self, name: str) -> Optional[Deployment]:
        """Public registry accessor (autoscaler/dashboard use this, not
        the private dict). Names still mid-deploy read as absent."""
        with self._lock:
            return self._deployments.get(name)

    def deployments(self) -> Dict[str, Deployment]:
        with self._lock:
            return {n: d for n, d in self._deployments.items()
                    if d is not None}

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-deployment data-plane snapshot (the ``/-/stats`` ingress
        payload): replica counts, logical load, batching telemetry."""
        return {name: dep.stats()
                for name, dep in sorted(self.deployments().items())}
