"""Autoregressive-decode microbenchmarks (the decode leg of the serve
suite).

Closed-loop token throughput through the iteration-level scheduler
(:class:`~tosem_tpu.serve.batching.DecodeQueue` over
:class:`~tosem_tpu.serve.backends.BertDecodeBackend`) against the naive
baseline the paged cache replaces: re-encoding the WHOLE prefix through
the causal prefill for every generated token (O(T²) per sequence, no KV
reuse). Both arms serve the same tiny-topology causal decoder with the
same seed, so their greedy token paths are identical — the A/B isolates
exactly what continuous batching + the paged cache buy.

Four scenario legs cover the decode fast paths on top of that:

- ``window`` — sliding-window paged decode at t8192 against the
  full-cache step program, with the live-page bound asserted
  (constant-memory long-context decode; the window arm's narrow rolling
  block table is the whole win off-chip).
- ``spec`` — speculative decoding (draft k=4 via prompt-lookup) against
  single-token decode, accepted-tokens/s with the two arms' greedy
  outputs pinned bit-identical.
- ``beam`` — n=4 beam fanout through COW page sharing, with the
  group-vs-single page-allocation ratio asserted <= 1.5x at equal
  prefix.
- ``prefix`` — radix prefix cache on vs off at 0.75 prefix share:
  admit-to-first-token (``max_new_tokens=1``) warm vs cold with the
  greedy outputs pinned bit-identical and the >=2x TTFT advantage
  hard-asserted, plus a multi-turn session leg proving suffix-only
  prefill via the prefill-token counters (zero per-admit recompiles).

Interleaved A/B rounds per the bench-noise protocol (both arms of a
round share the host phase; the speedup ratio is phase-immune). After
warmup the decode arm must never recompile — one step program per (page
config, max-batch) — which the bench ASSERTS via the replica's
compile-cache miss count before/after the timed rounds. The paged c16
leg additionally reports per-token p50/p99 latency rows (lower-is-
better floors) next to its throughput.

``python -m tosem_tpu.cli microbench --decode`` runs it
(``--scenario=window|beam|spec|prefix`` restricts to one scenario's
legs);
``--save`` / ``--check`` record/gate against
``results/bench_decode.json`` floors (min-of-rounds for throughput,
max-of-rounds ceilings for latency) in ``ci.sh --perf``.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from tosem_tpu.serve.bench_common import (SuiteEmitter, closed_loop,
                                          per_unit_percentiles)
from tosem_tpu.utils.results import ResultRow

# Gated by ci.sh --perf. The c16 arms and the speedup ratios are the
# acceptance surface: continuous batching >=3x the re-encode baseline
# (ISSUE 6), sliding-window >=2x full-cache at t8192, speculative k=4
# >=1.5x single-token, beam fanout tokens/s — floored well below
# measured so host noise can't flake the gate. The p50/p99 rows gate as
# CEILINGS (direction="lower" in the baseline).
GATED_DECODE_BENCHES = (
    "decode_paged_c1", "decode_paged_c16", "decode_speedup_c16",
    "decode_paged_c16_p50_ms", "decode_paged_c16_p99_ms",
    "decode_window_t8192", "decode_window_speedup_t8192",
    "decode_spec_c8", "decode_spec_speedup_c8",
    "decode_beam_c4",
    "decode_prefix_warm_ttft_ms", "decode_prefix_ttft_speedup",
)

# --scenario legs for `cli microbench --decode --scenario=...` and the
# tpu_capture decode_scenarios leg
SCENARIO_BENCHES = {
    "window": ("decode_full_t8192", "decode_window_t8192",
               "decode_window_speedup_t8192"),
    "spec": ("decode_single_c8", "decode_spec_c8",
             "decode_spec_speedup_c8"),
    "beam": ("decode_beam_c4", "decode_beam_pages_ratio"),
    "prefix": ("decode_prefix_cold_ttft_ms", "decode_prefix_warm_ttft_ms",
               "decode_prefix_ttft_speedup",
               "decode_prefix_session_suffix_frac"),
}

DEFAULT_BASELINE = "results/bench_decode.json"

# One model config for both arms (and the parity pin): tiny topology,
# page-multiple max_len, enough pages for 16 sequences of
# prompt+generated <= 3 pages each. 32 generated tokens per prompt is
# where the paged-vs-re-encode physics shows: the baseline's per-token
# cost GROWS with the prefix (O(T^2) per sequence) while the paged
# arm's stays one step-program share.
MODEL_KW = dict(max_batch=16, max_len=128, page_size=16, num_pages=96,
                max_new_tokens=32)
PROMPT_LEN = 12

# spec/beam scenario config: longer generations so draft acceptance and
# COW divergence have room to act, 8 concurrent sequences
SCEN_KW = dict(max_batch=8, max_len=192, page_size=16, num_pages=128,
               max_new_tokens=48)

# prefix scenario: 256-token prompts sharing a 192-token hot prefix
# (0.75 share, 12 whole pages); the suffix rides ONE wide multi-query
# chunk (suffix_q=64 on the XLA lowering), so a warm admit pays one
# dispatch where a cold admit pays the full 256-token prefill. The
# pool is kept small — pool-update bytes are a COMMON cost both arms
# pay per dispatch and only wash out the A/B contrast.
PREFIX_PLEN = 256
PREFIX_SHARE = 192
PREFIX_KW = dict(max_batch=8, max_len=288, page_size=16, num_pages=64,
                 max_new_tokens=48)

# window scenario: t8192 context, w1024 sliding window, one-lane pages
WIN_T = 8192
WIN_W = 1024
WIN_PAGE = 128
WIN_B = 4


def _prompt(i: int) -> Dict[str, Any]:
    return {"ids": [1 + ((i * 7 + j) % 126) for j in range(PROMPT_LEN)]}


class NaiveRecodeBackend:
    """The no-KV-cache baseline: every generated token re-runs the
    causal prefill over the whole prefix (padded to the page-multiple
    bucket palette), argmaxes the last row, appends, repeats. Same
    model, seed, and greedy rule as :class:`BertDecodeBackend`, so both
    arms emit identical tokens — this arm just recomputes every cached
    K/V from scratch each step."""

    def __init__(self, preset: str = "tiny", seed: int = 0,
                 max_len: int = 128, page_size: int = 16,
                 max_new_tokens: int = 16):
        import jax

        from tosem_tpu.models.bert import Bert, BertConfig
        cfg = BertConfig(vocab_size=128, max_len=max_len, dim=32,
                         heads=2, layers=2, mlp_dim=64, dropout=0.0)
        self.cfg = cfg
        self.page = page_size
        self.max_new_tokens = max_new_tokens
        self.model = Bert(cfg)
        self._vs = self.model.init(jax.random.PRNGKey(seed))
        self._prefill = self.model.prefill_fn(self._vs)
        from tosem_tpu.serve.backends import model_tag
        self._tag = model_tag("bert_recode", cfg, seed)
        self._lock = threading.Lock()

    def _compiled(self, pad_to: int):
        import numpy as np

        from tosem_tpu.serve.compile_cache import (DEFAULT_COMPILE_CACHE,
                                                   aot_compile, shape_key)
        key = shape_key(self._tag, (1, pad_to), self.cfg.dtype)
        return DEFAULT_COMPILE_CACHE.get_or_build(
            key, lambda: aot_compile(
                self._prefill, [((1, pad_to), np.int32),
                                ((1, pad_to), np.int32)]))

    def warmup(self, shapes) -> Dict[str, Any]:
        for pad_to in shapes:
            self._compiled(int(pad_to))
        return {"warmed": len(list(shapes))}

    def call(self, request: Dict[str, Any]) -> Any:
        import numpy as np
        toks = list(request["ids"])
        prompt_len = len(toks)
        with self._lock:
            for _ in range(self.max_new_tokens):
                T = len(toks)
                if T >= self.cfg.max_len:
                    break
                bucket = -(-T // self.page) * self.page
                ids = np.zeros((1, bucket), np.int32)
                mask = np.zeros((1, bucket), np.int32)
                ids[0, :T] = toks
                mask[0, :T] = 1
                logits, _, _ = self._compiled(bucket)(ids, mask)
                toks.append(int(np.argmax(
                    np.asarray(logits[0, T - 1], np.float32))))
        return {"tokens": toks, "generated": toks[prompt_len:],
                "prompt_len": prompt_len}


def _token_loop(handle, n_clients: int, min_s: float,
                samples: Optional[list] = None,
                count_of=None) -> float:
    """``n_clients`` threads, each submitting prompts closed-loop for
    >= ``min_s`` → generated tokens/s across the fleet. (Thin wrapper
    over the shared fleet in :mod:`tosem_tpu.serve.bench_common` —
    prompts cycle per client, completed calls weigh their generated
    token count; ``samples`` collects (latency, tokens) pairs for the
    per-token percentile rows.)"""
    return closed_loop(handle.call, n_clients, min_s,
                       lambda i, k: _prompt(i + k * n_clients),
                       count_of=count_of or
                       (lambda out: len(out["generated"])),
                       timeout=120.0, samples=samples)


# ---------------------------------------------------------------------------
# scenario legs


def _window_leg(em: SuiteEmitter, trials: int, min_s: float) -> None:
    """Sliding-window vs full-cache decode at t8192, step-program level:
    both arms run the SAME tiny causal decoder over a synthetic 8191-
    token history (allocator state is real — the window arm's cache was
    grown page-by-page with ``release_below`` applied, exactly the
    serving discipline), and each round times N fixed-state step calls
    per arm. The full arm gathers all 64 pages per token; the window
    arm's rolling table holds ceil(w/page)+2 pages, asserted, which is
    the constant-memory/constant-latency claim. Hard-asserts the >=2x
    speedup the ISSUE gates."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tosem_tpu.models.bert import Bert, BertConfig
    from tosem_tpu.serve.kv_cache import PagedKVCache

    T, W, PAGE, B = WIN_T, WIN_W, WIN_PAGE, WIN_B
    bound = -(-W // PAGE) + 2
    cfg = BertConfig(vocab_size=128, max_len=T, dim=32, heads=2,
                     layers=2, mlp_dim=64, dropout=0.0, dtype="float32")
    model = Bert(cfg)
    vs = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    n_full = T // PAGE

    def filled(cache):
        shape = tuple(cache.k_pool.shape)
        cache.set_pools(
            jnp.asarray(rng.standard_normal(shape), jnp.float32),
            jnp.asarray(rng.standard_normal(shape), jnp.float32))
        return cache

    # FULL arm: every page of the 8191-token history stays live
    full = PagedKVCache(B * n_full + 1, PAGE, layers=2, heads=2,
                        head_dim=16, dtype="float32")
    for b in range(B):
        full.create(f"s{b}")
        full.extend(f"s{b}", T - 1)
    filled(full)
    step_full = jax.jit(model.decode_step_fn(vs, page_size=PAGE,
                                             backend="xla"))
    tables_f = jnp.asarray(np.stack(
        [full.block_table(f"s{b}", n_full) for b in range(B)]))

    # WINDOW arm: grown page-by-page with eviction riding along, so the
    # pool never holds more than the rolling window (bounded memory)
    win = PagedKVCache(B * bound + 8, PAGE, layers=2, heads=2,
                       head_dim=16, dtype="float32")
    for b in range(B):
        cid = f"w{b}"
        win.create(cid)
        grown = 0
        while grown < T - 1:
            n = min(PAGE, T - 1 - grown)
            win.extend(cid, n)
            grown += n
            win.release_below(cid, grown + 1 - W)
        live = len(win.pages_of(cid))
        if live > bound:
            raise RuntimeError(
                f"window arm holds {live} live pages > "
                f"ceil(window/page)+2 = {bound} — eviction broke")
    filled(win)
    if win.stats()["pages_evicted_total"] <= 0:
        raise RuntimeError("window arm never evicted a page")
    table_w = bound + 2
    step_win = jax.jit(model.decode_multi_fn(
        vs, page_size=PAGE, q_tokens=1, backend="xla", window=W))
    tables_w = jnp.asarray(np.stack(
        [win.block_table(f"w{b}", table_w) for b in range(B)]))
    offs = jnp.asarray([win.page_offset(f"w{b}") for b in range(B)],
                       jnp.int32)

    ids1 = jnp.asarray(rng.integers(1, 127, B), jnp.int32)
    pos1 = jnp.full((B,), T - 1, jnp.int32)
    lens = jnp.full((B,), T, jnp.int32)
    idsK = ids1[:, None]
    posK = pos1[:, None]
    ones = jnp.ones((B,), jnp.int32)

    def run_full():
        return step_full(ids1, pos1, full.k_pool, full.v_pool,
                         tables_f, lens)

    def run_win():
        return step_win(idsK, posK, win.k_pool, win.v_pool, tables_w,
                        lens, ones, offs)

    def timed(fn, n_calls):
        t0 = time.perf_counter()
        out = None
        for _ in range(n_calls):
            out = fn()
        jax.block_until_ready(out[0])
        return time.perf_counter() - t0

    jax.block_until_ready(run_full()[0])     # compile outside the clock
    jax.block_until_ready(run_win()[0])
    dt = timed(run_full, 2) / 2
    n_calls = max(3, int(min_s / max(dt, 1e-4)))
    f_rates, w_rates, speedups = [], [], []
    for _ in range(max(trials, 1)):
        tf = timed(run_full, n_calls)
        tw = timed(run_win, n_calls)
        f_rates.append(n_calls * B / tf)
        w_rates.append(n_calls * B / tw)
        speedups.append(tf / tw)
    if max(speedups) < 2.0:
        raise RuntimeError(
            f"sliding-window decode at t{T} only {max(speedups):.2f}x "
            "the full-cache arm (>= 2x required)")
    em.emit("decode_full_t8192", "decode full-cache t8192 b4",
            f_rates, unit="tokens/s")
    row = em.emit("decode_window_t8192",
                  f"decode window w{W} t8192 b4", w_rates,
                  unit="tokens/s")
    if row is not None:
        row.extra["live_pages_per_seq"] = len(win.pages_of("w0"))
        row.extra["live_pages_bound"] = bound
        row.extra["pages_evicted"] = win.stats()["pages_evicted_total"]
    em.emit("decode_window_speedup_t8192",
            "decode window vs full-cache speedup t8192", speedups,
            unit="x")


def _spec_leg(em: SuiteEmitter, serve, trials: int,
              min_s: float) -> None:
    """Speculative (draft k=4, prompt-lookup drafter) vs single-token
    decode through the real serve data plane, 8 concurrent sequences.
    Pins the two arms' greedy outputs bit-identical (the accept-prefix
    + rollback construction) and hard-asserts the >=1.5x accepted-
    tokens/s the ISSUE gates."""
    from tosem_tpu.serve.backends import BertDecodeBackend
    from tosem_tpu.serve.batching import DecodePolicy

    serve.deploy("bench-spec", BertDecodeBackend, num_replicas=1,
                 max_retries=1,
                 init_kwargs=dict(SCEN_KW, spec_k=4),
                 decode_policy=DecodePolicy(max_active=8),
                 warmup_shapes=[16])
    serve.deploy("bench-single", BertDecodeBackend, num_replicas=1,
                 max_retries=1, init_kwargs=dict(SCEN_KW),
                 decode_policy=DecodePolicy(max_active=8),
                 warmup_shapes=[16])
    h_spec = serve.get_handle("bench-spec")
    h_single = serve.get_handle("bench-single")
    for i in range(3):                       # parity pin, several chains
        a = h_spec.call(_prompt(i), timeout=300.0)
        b = h_single.call(_prompt(i), timeout=300.0)
        if a["tokens"] != b["tokens"]:
            raise RuntimeError(
                f"speculative and single-token arms diverged on prompt "
                f"{i}: {a['tokens']} vs {b['tokens']}")
    single, spec, speedups = [], [], []
    for _ in range(max(trials, 1)):
        a = _token_loop(h_single, 8, min_s)
        b = _token_loop(h_spec, 8, min_s)
        single.append(a)
        spec.append(b)
        speedups.append(b / a if a else float("inf"))
    if max(speedups) < 1.5:
        raise RuntimeError(
            f"speculative k=4 only {max(speedups):.2f}x single-token "
            "accepted-tokens/s (>= 1.5x required)")
    em.emit("decode_single_c8", "decode single-token c8", single,
            unit="tokens/s")
    row = em.emit("decode_spec_c8", "decode speculative k4 c8", spec,
                  unit="tokens/s")
    if row is not None:
        import tosem_tpu.runtime as rt
        st = rt.get(serve.get_deployment("bench-spec")
                    ._replicas[0].cache_stats.remote(), timeout=60.0)
        if st.get("spec_proposed"):
            row.extra["acceptance_rate"] = round(
                st["spec_accepted"] / st["spec_proposed"], 3)
    em.emit("decode_spec_speedup_c8",
            "decode speculative vs single-token speedup c8", speedups,
            unit="x")
    serve.delete("bench-spec")
    serve.delete("bench-single")


def _beam_leg(em: SuiteEmitter, serve, trials: int,
              min_s: float) -> None:
    """n=4 beam fanout through the serve data plane (tokens/s counts
    every branch's committed tokens), plus the COW page-sharing proof:
    a 4-branch group at equal prefix length must allocate <= 1.5x the
    pages of a single sequence (hard assert + lower-is-better row)."""
    from tosem_tpu.serve.backends import BertDecodeBackend
    from tosem_tpu.serve.batching import DecodePolicy, SamplingPolicy

    # page-sharing proof on a raw backend: multi-page prefix, measured
    # immediately after admit (prefix shared, branches not yet diverged)
    probe = BertDecodeBackend(**SCEN_KW)
    long_prompt = {"ids": [1 + (j % 126) for j in range(48)]}
    probe.admit("single", dict(long_prompt))
    single_pages = probe.cache.stats()["pages_used"]
    probe.admit("group", {**long_prompt, "n": 4, "beam": True})
    group_pages = probe.cache.stats()["pages_used"] - single_pages
    ratio = group_pages / max(single_pages, 1)
    if ratio > 1.5:
        raise RuntimeError(
            f"beam n=4 allocated {group_pages} pages vs single "
            f"{single_pages} ({ratio:.2f}x > 1.5x) — COW sharing broke")
    probe.release("single")
    probe.release("group")
    em.emit("decode_beam_pages_ratio",
            "beam n4 vs single page-allocation ratio", [ratio],
            unit="x", lower_is_better=True)

    serve.deploy("bench-beam", BertDecodeBackend, num_replicas=1,
                 max_retries=1, init_kwargs=dict(SCEN_KW),
                 decode_policy=DecodePolicy(
                     max_active=8,
                     sampling=SamplingPolicy(n=4, beam=True)),
                 warmup_shapes=[16])
    h = serve.get_handle("bench-beam")
    out = h.call(_prompt(0), timeout=300.0)
    if len(out["beams"]) != 4:
        raise RuntimeError(f"expected 4 beams, got {len(out['beams'])}")
    count = lambda out: sum(len(e["generated"]) for e in out["beams"])
    rates = []
    for _ in range(max(trials, 1)):
        rates.append(_token_loop(h, 2, min_s, count_of=count))
    em.emit("decode_beam_c4", "decode beam n4 c2 all-branch tokens",
            rates, unit="tokens/s")
    serve.delete("bench-beam")


def _prefix_leg(em: SuiteEmitter, serve, trials: int,
                min_s: float) -> None:
    """Prefix-cache A/B at 0.75 prefix share: admit-to-first-token
    (per-request ``max_new_tokens=1`` — the sequence finishes AT admit,
    so call latency IS TTFT) with the radix cache on vs off, 128-token
    prompts sharing a 96-token hot prefix. Interleaved rounds; the two
    arms' greedy outputs are pinned bit-identical first, the warm arm's
    >=2x TTFT advantage is hard-asserted, and a multi-turn session leg
    proves suffix-only prefill via the backend's prefill-token counters
    (with zero per-admit recompiles)."""
    import tosem_tpu.runtime as rt
    from tosem_tpu.serve.backends import BertDecodeBackend
    from tosem_tpu.serve.batching import DecodePolicy

    shared = [1 + ((7 * j) % 126) for j in range(PREFIX_SHARE)]

    def prefix_prompt(i: int) -> Dict[str, Any]:
        return {"ids": shared + [1 + ((i * 11 + j) % 126)
                                 for j in range(PREFIX_PLEN
                                                - PREFIX_SHARE)]}

    # TTFT A/B on raw in-process backends (the beam leg's probe idiom):
    # admit latency IS the quantity under test, so the arms must not
    # hide behind the data plane's per-call overhead
    warm = BertDecodeBackend(**PREFIX_KW)
    cold = BertDecodeBackend(prefix_cache=False, **PREFIX_KW)

    # parity pin (and warm-arm seeding): prompt 0 populates the radix
    # index, prompts 1..3 take the suffix-prefill hit path — their
    # greedy streams must match the cold arm's bit for bit
    for i in range(4):
        a = warm.call(dict(prefix_prompt(i), max_new_tokens=8))
        b = cold.call(dict(prefix_prompt(i), max_new_tokens=8))
        if a["tokens"] != b["tokens"]:
            raise RuntimeError(
                f"prefix-hit and cold-prefill arms diverged on prompt "
                f"{i}: {a['tokens']} vs {b['tokens']}")

    def ttft_ms(backend, n: int, base: int) -> float:
        total = 0.0
        for i in range(n):
            req = dict(prefix_prompt(base + i), max_new_tokens=1)
            t0 = time.perf_counter()
            backend.admit(f"ttft/{base + i}", req)
            total += time.perf_counter() - t0
            backend.release(f"ttft/{base + i}")
        return total * 1000.0 / n

    from tosem_tpu.serve.compile_cache import DEFAULT_COMPILE_CACHE
    misses_before = DEFAULT_COMPILE_CACHE.stats()["misses"]
    cold_ms, warm_ms, speedups = [], [], []
    per_round = 12
    for r in range(max(trials, 1)):
        # one A/B round, both arms in the same host phase; fresh
        # suffixes per round so the COLD arm never amortizes anything
        base = 4 + r * per_round
        a = ttft_ms(cold, per_round, base)
        b = ttft_ms(warm, per_round, base)
        cold_ms.append(a)
        warm_ms.append(b)
        speedups.append(a / b if b else float("inf"))
    st = warm.cache_stats()
    if not st.get("prefix_hits"):
        raise RuntimeError(
            "warm arm recorded zero prefix hits — the radix index "
            "never engaged and the A/B measured nothing")
    if max(speedups) < 2.0:
        raise RuntimeError(
            f"prefix-cache TTFT only {max(speedups):.2f}x cold prefill "
            "at 0.75 prefix share (>= 2x required)")
    if DEFAULT_COMPILE_CACHE.stats()["misses"] != misses_before:
        raise RuntimeError(
            "prefix A/B recompiled during the timed rounds "
            f"({DEFAULT_COMPILE_CACHE.stats()['misses'] - misses_before}"
            " new compile-cache misses)")

    em.emit("decode_prefix_cold_ttft_ms",
            "decode cold-prefill TTFT share0.75", cold_ms,
            unit="ms", lower_is_better=True)
    row = em.emit("decode_prefix_warm_ttft_ms",
                  "decode prefix-hit TTFT share0.75", warm_ms,
                  unit="ms", lower_is_better=True)
    if row is not None:
        hits = st["prefix_hits"]
        row.extra["prefix_hit_rate"] = round(
            hits / max(hits + st["prefix_misses"], 1), 3)
        row.extra["pages_reused"] = st["prefix_pages_reused"]
        row.extra["pages_prefilled"] = st["prefix_pages_prefilled"]
    em.emit("decode_prefix_ttft_speedup",
            "decode prefix-hit vs cold-prefill TTFT speedup share0.75",
            speedups, unit="x")

    # multi-turn session leg: turn 2 replays turn 1's history + 2 new
    # tokens; the backend must prefill ONLY the suffix (history KV
    # stays resident under the session key) — asserted exactly via the
    # prefill-token counter delta, with zero recompiles
    serve.deploy("bench-prefix-sess", BertDecodeBackend, num_replicas=1,
                 max_retries=1, init_kwargs=dict(PREFIX_KW),
                 decode_policy=DecodePolicy(max_active=8, session=True),
                 warmup_shapes=[16])
    h = serve.get_handle("bench-prefix-sess")
    dep = serve.get_deployment("bench-prefix-sess")

    def sess_stats():
        return rt.get(dep._replicas[0].stats.remote(), timeout=60.0)

    fracs = []
    for r in range(max(trials, 1)):
        turn1 = {"ids": prefix_prompt(100 + r)["ids"],
                 "session": f"bench/{r}", "max_new_tokens": 8}
        hist = h.call(turn1, timeout=300.0)["tokens"]
        ids2 = hist + [9, 9]
        before = sess_stats()
        out2 = h.call({"ids": ids2, "session": f"bench/{r}",
                       "max_new_tokens": 8}, timeout=300.0)
        after = sess_stats()
        prefilled = after["prefill_tokens"] - before["prefill_tokens"]
        # session resume holds len(hist)-1 positions; the admit feeds
        # exactly the suffix (history's last token + the 2 new ones)
        want_suffix = len(ids2) - (len(hist) - 1)
        if prefilled != want_suffix:
            raise RuntimeError(
                f"session turn 2 prefilled {prefilled} tokens, "
                f"expected the {want_suffix}-token suffix only")
        if after["compile_cache"]["misses"] != \
                before["compile_cache"]["misses"]:
            raise RuntimeError("session resume recompiled at admit")
        if out2["tokens"][:len(ids2)] != ids2:
            raise RuntimeError("session turn 2 lost its history")
        fracs.append(prefilled / len(ids2))
    em.emit("decode_prefix_session_suffix_frac",
            "decode session turn-2 prefilled-token fraction", fracs,
            unit="frac", lower_is_better=True)
    serve.delete("bench-prefix-sess")


def run_decode_benchmarks(trials: int = 3, min_s: float = 0.5,
                          quiet: bool = False,
                          only: Optional[set] = None) -> List[ResultRow]:
    """Interleaved A/B decode benches; ``only`` restricts bench_ids."""
    import tosem_tpu.runtime as rt
    from tosem_tpu.serve.backends import BertDecodeBackend
    from tosem_tpu.serve.batching import DecodePolicy
    from tosem_tpu.serve.core import Serve

    em = SuiteEmitter("decode", only)
    want = em.want

    def emit(bid, name, vals, unit="tokens/s", lower_is_better=False):
        return em.emit(bid, name, vals, unit=unit,
                       lower_is_better=lower_is_better)

    own_runtime = not rt.is_initialized()
    if own_runtime:
        rt.init(num_workers=2, memory_monitor=False)

    if any(want(b) for b in SCENARIO_BENCHES["window"]):
        _window_leg(em, trials, min_s)

    base_ids = ("decode_naive_c1", "decode_paged_c1", "decode_naive_c16",
                "decode_paged_c16", "decode_speedup_c16",
                "decode_paged_c16_p50_ms", "decode_paged_c16_p99_ms")
    run_base = any(want(b) for b in base_ids)
    run_spec = any(want(b) for b in SCENARIO_BENCHES["spec"])
    run_beam = any(want(b) for b in SCENARIO_BENCHES["beam"])
    run_prefix = any(want(b) for b in SCENARIO_BENCHES["prefix"])

    serve = Serve() if (run_base or run_spec or run_beam
                        or run_prefix) else None
    if run_base:
        # prompt bucket (one page) is the only prefill shape the paged
        # arm sees; the naive arm re-encodes through every growth bucket
        buckets = list(range(16, MODEL_KW["max_len"] + 1, 16))
        serve.deploy("bench-decode", BertDecodeBackend,
                     num_replicas=1, max_retries=1,
                     init_kwargs=dict(MODEL_KW),
                     decode_policy=DecodePolicy(max_active=16),
                     warmup_shapes=[16])
        serve.deploy("bench-recode", NaiveRecodeBackend,
                     num_replicas=1, max_retries=1,
                     init_kwargs=dict(
                         max_len=MODEL_KW["max_len"],
                         page_size=MODEL_KW["page_size"],
                         max_new_tokens=MODEL_KW["max_new_tokens"]),
                     warmup_shapes=buckets)
        h_paged = serve.get_handle("bench-decode")
        h_naive = serve.get_handle("bench-recode")
        dep_paged = serve.get_deployment("bench-decode")

        # pre-warm both arms end to end (first call compiles anything
        # the declared warmup missed) AND pin parity: same greedy tokens
        out_p = h_paged.call(_prompt(0), timeout=300.0)
        out_n = h_naive.call(_prompt(0), timeout=300.0)
        if out_p["tokens"] != out_n["tokens"]:
            raise RuntimeError(
                f"paged and re-encode arms diverged: {out_p['tokens']} "
                f"vs {out_n['tokens']}")

        def cache_misses():
            st = rt.get(dep_paged._replicas[0].stats.remote(),
                        timeout=60.0)
            return st["compile_cache"]["misses"]

        misses_before = cache_misses()
        naive1, paged1, naive16, paged16, speedups = [], [], [], [], []
        p50s, p99s = [], []
        for _ in range(max(trials, 1)):
            # one A/B round: every leg sees the same host phase
            if want("decode_naive_c1") or want("decode_paged_c1"):
                naive1.append(_token_loop(h_naive, 1, min_s))
                paged1.append(_token_loop(h_paged, 1, min_s))
            samples: list = []
            a = _token_loop(h_naive, 16, min_s)
            b = _token_loop(h_paged, 16, min_s, samples=samples)
            naive16.append(a)
            paged16.append(b)
            speedups.append(b / a if a else float("inf"))
            p50, p99 = per_unit_percentiles(samples, (50, 99))
            p50s.append(p50)
            p99s.append(p99)
        misses_after = cache_misses()
        if misses_after != misses_before:
            # the one-program-per-(page config, max-batch) contract:
            # steps after warmup must be pure cache hits, whatever the
            # packing
            raise RuntimeError(
                f"decode arm recompiled during the timed rounds "
                f"({misses_after - misses_before} new compile-cache "
                "misses)")

        emit("decode_naive_c1", "decode re-encode baseline c1", naive1)
        emit("decode_paged_c1", "decode paged c1", paged1)
        emit("decode_naive_c16", "decode re-encode baseline c16",
             naive16)
        row = emit("decode_paged_c16", "decode paged c16", paged16)
        if row is not None:
            row.extra["compile_cache_misses_during_rounds"] = (
                misses_after - misses_before)
        emit("decode_speedup_c16",
             "decode paged vs re-encode speedup c16", speedups,
             unit="x")
        # per-token latency next to the throughput (satellite): the
        # caller-visible amortized cost per generated token, floored as
        # a CEILING (lower is better)
        emit("decode_paged_c16_p50_ms", "decode paged c16 p50 latency",
             p50s, unit="ms/token", lower_is_better=True)
        emit("decode_paged_c16_p99_ms", "decode paged c16 p99 latency",
             p99s, unit="ms/token", lower_is_better=True)

        serve.delete("bench-decode")
        serve.delete("bench-recode")

    if run_spec:
        _spec_leg(em, serve, trials, min_s)
    if run_beam:
        _beam_leg(em, serve, trials, min_s)
    if run_prefix:
        _prefix_leg(em, serve, trials, min_s)

    if own_runtime:
        rt.shutdown()
    return em.flush(quiet)
