"""Autoregressive-decode microbenchmarks (the decode leg of the serve
suite).

Closed-loop token throughput through the iteration-level scheduler
(:class:`~tosem_tpu.serve.batching.DecodeQueue` over
:class:`~tosem_tpu.serve.backends.BertDecodeBackend`) against the naive
baseline the paged cache replaces: re-encoding the WHOLE prefix through
the causal prefill for every generated token (O(T²) per sequence, no KV
reuse). Both arms serve the same tiny-topology causal decoder with the
same seed, so their greedy token paths are identical — the A/B isolates
exactly what continuous batching + the paged cache buy.

Interleaved A/B rounds per the bench-noise protocol (both arms of a
round share the host phase; the speedup ratio is phase-immune), at 1 and
16 concurrent sequences. After warmup the decode arm must never
recompile — one step program per (page config, max-batch) — which the
bench ASSERTS via the replica's compile-cache miss count before/after
the timed rounds.

``python -m tosem_tpu.cli microbench --decode`` runs it; ``--save`` /
``--check`` record/gate against ``results/bench_decode.json`` floors
(min-of-rounds, like the other suites) in ``ci.sh --perf``.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from tosem_tpu.serve.bench_common import SuiteEmitter, closed_loop
from tosem_tpu.utils.results import ResultRow

# Gated by ci.sh --perf. The c16 arms and the speedup ratio are the
# acceptance surface: >=3x tokens/s at 16 concurrent sequences vs the
# re-encode baseline (ISSUE 6), floored well below measured so host
# noise can't flake the gate.
GATED_DECODE_BENCHES = (
    "decode_paged_c1", "decode_paged_c16", "decode_speedup_c16",
)

DEFAULT_BASELINE = "results/bench_decode.json"

# One model config for both arms (and the parity pin): tiny topology,
# page-multiple max_len, enough pages for 16 sequences of
# prompt+generated <= 3 pages each. 32 generated tokens per prompt is
# where the paged-vs-re-encode physics shows: the baseline's per-token
# cost GROWS with the prefix (O(T^2) per sequence) while the paged
# arm's stays one step-program share.
MODEL_KW = dict(max_batch=16, max_len=128, page_size=16, num_pages=96,
                max_new_tokens=32)
PROMPT_LEN = 12


def _prompt(i: int) -> Dict[str, Any]:
    return {"ids": [1 + ((i * 7 + j) % 126) for j in range(PROMPT_LEN)]}


class NaiveRecodeBackend:
    """The no-KV-cache baseline: every generated token re-runs the
    causal prefill over the whole prefix (padded to the page-multiple
    bucket palette), argmaxes the last row, appends, repeats. Same
    model, seed, and greedy rule as :class:`BertDecodeBackend`, so both
    arms emit identical tokens — this arm just recomputes every cached
    K/V from scratch each step."""

    def __init__(self, preset: str = "tiny", seed: int = 0,
                 max_len: int = 128, page_size: int = 16,
                 max_new_tokens: int = 16):
        import jax

        from tosem_tpu.models.bert import Bert, BertConfig
        cfg = BertConfig(vocab_size=128, max_len=max_len, dim=32,
                         heads=2, layers=2, mlp_dim=64, dropout=0.0)
        self.cfg = cfg
        self.page = page_size
        self.max_new_tokens = max_new_tokens
        self.model = Bert(cfg)
        self._vs = self.model.init(jax.random.PRNGKey(seed))
        self._prefill = self.model.prefill_fn(self._vs)
        from tosem_tpu.serve.backends import model_tag
        self._tag = model_tag("bert_recode", cfg, seed)
        self._lock = threading.Lock()

    def _compiled(self, pad_to: int):
        import numpy as np

        from tosem_tpu.serve.compile_cache import (DEFAULT_COMPILE_CACHE,
                                                   aot_compile, shape_key)
        key = shape_key(self._tag, (1, pad_to), self.cfg.dtype)
        return DEFAULT_COMPILE_CACHE.get_or_build(
            key, lambda: aot_compile(
                self._prefill, [((1, pad_to), np.int32),
                                ((1, pad_to), np.int32)]))

    def warmup(self, shapes) -> Dict[str, Any]:
        for pad_to in shapes:
            self._compiled(int(pad_to))
        return {"warmed": len(list(shapes))}

    def call(self, request: Dict[str, Any]) -> Any:
        import numpy as np
        toks = list(request["ids"])
        prompt_len = len(toks)
        with self._lock:
            for _ in range(self.max_new_tokens):
                T = len(toks)
                if T >= self.cfg.max_len:
                    break
                bucket = -(-T // self.page) * self.page
                ids = np.zeros((1, bucket), np.int32)
                mask = np.zeros((1, bucket), np.int32)
                ids[0, :T] = toks
                mask[0, :T] = 1
                logits, _, _ = self._compiled(bucket)(ids, mask)
                toks.append(int(np.argmax(
                    np.asarray(logits[0, T - 1], np.float32))))
        return {"tokens": toks, "generated": toks[prompt_len:],
                "prompt_len": prompt_len}


def _token_loop(handle, n_clients: int, min_s: float) -> float:
    """``n_clients`` threads, each submitting prompts closed-loop for
    >= ``min_s`` → generated tokens/s across the fleet. (Thin wrapper
    over the shared fleet in :mod:`tosem_tpu.serve.bench_common` —
    prompts cycle per client, completed calls weigh their generated
    token count.)"""
    return closed_loop(handle.call, n_clients, min_s,
                       lambda i, k: _prompt(i + k * n_clients),
                       count_of=lambda out: len(out["generated"]),
                       timeout=120.0)


def run_decode_benchmarks(trials: int = 3, min_s: float = 0.5,
                          quiet: bool = False,
                          only: Optional[set] = None) -> List[ResultRow]:
    """Interleaved A/B decode benches; ``only`` restricts bench_ids."""
    import tosem_tpu.runtime as rt
    from tosem_tpu.serve.backends import BertDecodeBackend
    from tosem_tpu.serve.batching import DecodePolicy
    from tosem_tpu.serve.core import Serve

    em = SuiteEmitter("decode", only)
    want = em.want

    def emit(bid, name, vals, unit="tokens/s"):
        return em.emit(bid, name, vals, unit=unit)

    own_runtime = not rt.is_initialized()
    if own_runtime:
        rt.init(num_workers=2, memory_monitor=False)

    serve = Serve()
    # prompt bucket (one page) is the only prefill shape the paged arm
    # sees; the naive arm re-encodes through every growth bucket
    buckets = list(range(16, MODEL_KW["max_len"] + 1, 16))
    serve.deploy("bench-decode", BertDecodeBackend,
                 num_replicas=1, max_retries=1, init_kwargs=dict(MODEL_KW),
                 decode_policy=DecodePolicy(max_active=16),
                 warmup_shapes=[16])
    serve.deploy("bench-recode", NaiveRecodeBackend,
                 num_replicas=1, max_retries=1,
                 init_kwargs=dict(max_len=MODEL_KW["max_len"],
                                  page_size=MODEL_KW["page_size"],
                                  max_new_tokens=MODEL_KW["max_new_tokens"]),
                 warmup_shapes=buckets)
    h_paged = serve.get_handle("bench-decode")
    h_naive = serve.get_handle("bench-recode")
    dep_paged = serve.get_deployment("bench-decode")

    # pre-warm both arms end to end (first call compiles anything the
    # declared warmup missed) AND pin parity: same greedy tokens
    out_p = h_paged.call(_prompt(0), timeout=300.0)
    out_n = h_naive.call(_prompt(0), timeout=300.0)
    if out_p["tokens"] != out_n["tokens"]:
        raise RuntimeError(
            f"paged and re-encode arms diverged: {out_p['tokens']} vs "
            f"{out_n['tokens']}")

    def cache_misses():
        st = rt.get(dep_paged._replicas[0].stats.remote(), timeout=60.0)
        return st["compile_cache"]["misses"]

    misses_before = cache_misses()
    naive1, paged1, naive16, paged16, speedups = [], [], [], [], []
    for _ in range(max(trials, 1)):
        # one A/B round: every leg sees the same host phase
        if want("decode_naive_c1") or want("decode_paged_c1"):
            naive1.append(_token_loop(h_naive, 1, min_s))
            paged1.append(_token_loop(h_paged, 1, min_s))
        a = _token_loop(h_naive, 16, min_s)
        b = _token_loop(h_paged, 16, min_s)
        naive16.append(a)
        paged16.append(b)
        speedups.append(b / a if a else float("inf"))
    misses_after = cache_misses()
    if misses_after != misses_before:
        # the one-program-per-(page config, max-batch) contract: steps
        # after warmup must be pure cache hits, whatever the packing
        raise RuntimeError(
            f"decode arm recompiled during the timed rounds "
            f"({misses_after - misses_before} new compile-cache misses)")

    emit("decode_naive_c1", "decode re-encode baseline c1", naive1)
    emit("decode_paged_c1", "decode paged c1", paged1)
    emit("decode_naive_c16", "decode re-encode baseline c16", naive16)
    row = emit("decode_paged_c16", "decode paged c16", paged16)
    if row is not None:
        row.extra["compile_cache_misses_during_rounds"] = (
            misses_after - misses_before)
    emit("decode_speedup_c16", "decode paged vs re-encode speedup c16",
         speedups, unit="x")

    serve.delete("bench-decode")
    serve.delete("bench-recode")
    if own_runtime:
        rt.shutdown()
    return em.flush(quiet)
