"""Serving layer (L6): deployments, router, HTTP ingress, streaming C API.

Ray Serve's controller/router/replica architecture (SURVEY §2.1) rebuilt on
the single-controller actor runtime, plus the DeepSpeech native-client
streaming surface (``deepspeech.h:107-358``) as a real C ABI
(``native/speech_api.cpp``) fed by JAX callbacks — and an adaptive
micro-batching data plane (:mod:`tosem_tpu.serve.batching`) that
coalesces concurrent requests into padding-bucketed batches on the flash
kernels, behind a deploy-time-warmed compiled-program cache
(:mod:`tosem_tpu.serve.compile_cache`).
"""
from tosem_tpu.control.admission import Overloaded, SLOConfig
from tosem_tpu.serve.autoscale import ServeAutoscaler, ServeScaleConfig
from tosem_tpu.serve.backends import BertEncodeBackend
from tosem_tpu.serve.batching import (BatchedFuture, BatchingReplica,
                                      BatchPolicy, BatchQueue)
from tosem_tpu.serve.breaker import CircuitBreaker, CircuitOpen
from tosem_tpu.serve.cluster_serve import (ClusterDeployment,
                                           ClusterHandle, ClusterServe,
                                           PlacementError)
from tosem_tpu.serve.compile_cache import (DEFAULT_COMPILE_CACHE,
                                           CompileCache)
from tosem_tpu.serve.core import Deployment, Handle, Serve, ServeFuture
from tosem_tpu.serve.http import HttpIngress
from tosem_tpu.serve.router import (NoReplicaAvailable, RemoteRouter,
                                    ReplicaAppError, RouterCore,
                                    RouterPolicy)
from tosem_tpu.serve.speech import (CStreamingModel, SpeechBatchBackend,
                                    SpeechStreamBackend, StreamingClient,
                                    greedy_ctc_text)

__all__ = [
    "Serve", "Deployment", "Handle", "ServeFuture", "HttpIngress",
    "ClusterServe", "ClusterDeployment", "ClusterHandle",
    "PlacementError", "RouterCore", "RouterPolicy", "RemoteRouter",
    "NoReplicaAvailable", "ReplicaAppError",
    "CircuitBreaker", "CircuitOpen", "Overloaded", "SLOConfig",
    "ServeAutoscaler", "ServeScaleConfig",
    "BatchPolicy", "BatchQueue", "BatchedFuture", "BatchingReplica",
    "CompileCache", "DEFAULT_COMPILE_CACHE",
    "BertEncodeBackend", "SpeechBatchBackend",
    "CStreamingModel", "SpeechStreamBackend", "StreamingClient",
    "greedy_ctc_text",
]
