"""Serving layer (L6): deployments, router, HTTP ingress, streaming C API.

Ray Serve's controller/router/replica architecture (SURVEY §2.1) rebuilt on
the single-controller actor runtime, plus the DeepSpeech native-client
streaming surface (``deepspeech.h:107-358``) as a real C ABI
(``native/speech_api.cpp``) fed by JAX callbacks.
"""
from tosem_tpu.serve.autoscale import ServeAutoscaler, ServeScaleConfig
from tosem_tpu.serve.breaker import CircuitBreaker, CircuitOpen
from tosem_tpu.serve.core import Deployment, Handle, Serve, ServeFuture
from tosem_tpu.serve.http import HttpIngress
from tosem_tpu.serve.speech import (CStreamingModel, SpeechStreamBackend,
                                    StreamingClient, greedy_ctc_text)

__all__ = [
    "Serve", "Deployment", "Handle", "ServeFuture", "HttpIngress",
    "CircuitBreaker", "CircuitOpen",
    "CStreamingModel", "SpeechStreamBackend", "StreamingClient",
    "greedy_ctc_text",
]
