"""HTTP ingress for Serve-lite (the reference's proxy role).

``python/ray/serve/api.py:210`` starts an HTTP proxy actor translating
``POST /<endpoint>`` into router calls; single-controller here, so the
proxy is a threaded stdlib HTTP server in the driver process. JSON in,
JSON out; backend errors map to 500, unknown endpoints to 404.

The controller argument duck-types: a
:class:`~tosem_tpu.serve.core.Serve` (in-process deployments) or a
:class:`~tosem_tpu.serve.cluster_serve.ClusterServe` (node-spanning
deployments behind the router tier) both expose ``get_deployment`` /
``get_handle`` / ``list_deployments`` / ``stats``. Against the cluster
plane, ``POST /<endpoint>?key=<affinity>`` pins the request to its
consistent-hash replica, and ``/-/stats`` serves the router-tier
rollup (per-node queue depth, routed-vs-spilled counters).

``POST /<endpoint>?stream=1`` switches a decode deployment to chunked
transfer: one JSON line per committed token batch as the scheduler
emits it, then a final ``{"result": ...}`` line. The scheduler thread
never writes the socket — tokens bridge through a queue, so a slow or
dropped client stalls only its own ingress thread.
"""
from __future__ import annotations

import inspect
import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlsplit

from tosem_tpu.serve.core import Serve


class HttpIngress:
    def __init__(self, serve: Serve, host: str = "127.0.0.1",
                 port: int = 0, request_timeout: float = 30.0):
        ingress = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):     # quiet
                pass

            def do_POST(self):
                parts = urlsplit(self.path)
                name = parts.path.strip("/")
                if serve.get_deployment(name) is None:
                    self._reply(404, {"error": f"no endpoint {name!r}"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    request = json.loads(self.rfile.read(n) or b"null")
                    handle = serve.get_handle(name)
                    qs = parse_qs(parts.query)
                    key = qs.get("key", [None])[0]
                    stream = qs.get("stream", ["0"])[0] \
                        not in ("0", "", "false")
                    if stream and hasattr(handle, "stream"):
                        self._stream(handle, request)
                        return
                    # affinity key: only a handle whose call() declares
                    # key= routes on it (the cluster handle); detected
                    # by SIGNATURE, never by catching TypeError around
                    # the live call — a backend's own TypeError must
                    # not trigger a second execution of the request
                    kwargs = {}
                    if key is not None and "key" in inspect.signature(
                            handle.call).parameters:
                        kwargs["key"] = key
                    result = handle.call(
                        request, timeout=ingress.request_timeout,
                        **kwargs)
                    self._reply(200, {"result": result})
                except Exception as e:  # backend failure → 500, not a crash
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})

            def _stream(self, handle, request) -> None:
                """Per-token chunked streaming: the decode scheduler
                pushes committed tokens into a queue (its callback
                never blocks on this socket); THIS thread drains the
                queue into chunked-transfer JSON lines."""
                q: "queue.Queue" = queue.Queue()

                def on_token(tokens, done):
                    q.put((tokens, done))

                worker_err = []

                def run():
                    try:
                        result = handle.stream(
                            request, on_token,
                            timeout=ingress.request_timeout)
                        q.put(("__result__", result))
                    except BaseException as e:
                        worker_err.append(e)
                        q.put(("__error__", e))

                t = threading.Thread(target=run, daemon=True,
                                     name="serve-http-stream")
                t.start()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    while True:
                        kind, payload = q.get(
                            timeout=ingress.request_timeout)
                        if kind == "__error__":
                            self._chunk({"error":
                                         f"{type(payload).__name__}: "
                                         f"{payload}"})
                            break
                        if kind == "__result__":
                            self._chunk({"result": payload})
                            break
                        self._chunk({"tokens": list(kind),
                                     "done": bool(payload)})
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionError, OSError,
                        queue.Empty):
                    pass     # client gone / stalled: fails alone

            def _chunk(self, payload) -> None:
                body = json.dumps(payload).encode() + b"\n"
                self.wfile.write(f"{len(body):x}\r\n".encode()
                                 + body + b"\r\n")
                self.wfile.flush()

            def do_GET(self):
                if self.path.rstrip("/") in ("", "/-", "/-/routes"):
                    self._reply(200, {"routes": serve.list_deployments()})
                elif self.path.rstrip("/") == "/-/stats":
                    # data-plane telemetry: queue depth, batch sizes,
                    # per-request outcome counts — the operator's view
                    # of whether batching is actually engaging
                    payload = {"deployments": serve.stats()}
                    # distributed-training jobs share the stats surface
                    # (dp size, step, examples/s) when any are live
                    try:
                        from tosem_tpu.train.distributed import jobs_stats
                        train = jobs_stats()
                        if train:
                            payload["train"] = train
                    except Exception:
                        pass     # telemetry never fails the endpoint
                    self._reply(200, payload)
                else:
                    self._reply(404, {"error": "POST to /<endpoint>"})

            def _reply(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.request_timeout = request_timeout
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="serve-http")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=2.0)
