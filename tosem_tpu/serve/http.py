"""HTTP ingress for Serve-lite (the reference's proxy role).

``python/ray/serve/api.py:210`` starts an HTTP proxy actor translating
``POST /<endpoint>`` into router calls; single-controller here, so the
proxy is a threaded stdlib HTTP server in the driver process. JSON in,
JSON out; backend errors map to 500, unknown endpoints to 404.

The controller argument duck-types: a
:class:`~tosem_tpu.serve.core.Serve` (in-process deployments) or a
:class:`~tosem_tpu.serve.cluster_serve.ClusterServe` (node-spanning
deployments behind the router tier) both expose ``get_deployment`` /
``get_handle`` / ``list_deployments`` / ``stats``. Against the cluster
plane, ``POST /<endpoint>?key=<affinity>`` pins the request to its
consistent-hash replica, and ``/-/stats`` serves the router-tier
rollup (per-node queue depth, routed-vs-spilled counters).
"""
from __future__ import annotations

import inspect
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlsplit

from tosem_tpu.serve.core import Serve


class HttpIngress:
    def __init__(self, serve: Serve, host: str = "127.0.0.1",
                 port: int = 0, request_timeout: float = 30.0):
        ingress = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):     # quiet
                pass

            def do_POST(self):
                parts = urlsplit(self.path)
                name = parts.path.strip("/")
                if serve.get_deployment(name) is None:
                    self._reply(404, {"error": f"no endpoint {name!r}"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    request = json.loads(self.rfile.read(n) or b"null")
                    handle = serve.get_handle(name)
                    key = parse_qs(parts.query).get("key", [None])[0]
                    # affinity key: only a handle whose call() declares
                    # key= routes on it (the cluster handle); detected
                    # by SIGNATURE, never by catching TypeError around
                    # the live call — a backend's own TypeError must
                    # not trigger a second execution of the request
                    kwargs = {}
                    if key is not None and "key" in inspect.signature(
                            handle.call).parameters:
                        kwargs["key"] = key
                    result = handle.call(
                        request, timeout=ingress.request_timeout,
                        **kwargs)
                    self._reply(200, {"result": result})
                except Exception as e:  # backend failure → 500, not a crash
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})

            def do_GET(self):
                if self.path.rstrip("/") in ("", "/-", "/-/routes"):
                    self._reply(200, {"routes": serve.list_deployments()})
                elif self.path.rstrip("/") == "/-/stats":
                    # data-plane telemetry: queue depth, batch sizes,
                    # per-request outcome counts — the operator's view
                    # of whether batching is actually engaging
                    payload = {"deployments": serve.stats()}
                    # distributed-training jobs share the stats surface
                    # (dp size, step, examples/s) when any are live
                    try:
                        from tosem_tpu.train.distributed import jobs_stats
                        train = jobs_stats()
                        if train:
                            payload["train"] = train
                    except Exception:
                        pass     # telemetry never fails the endpoint
                    self._reply(200, payload)
                else:
                    self._reply(404, {"error": "POST to /<endpoint>"})

            def _reply(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.request_timeout = request_timeout
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="serve-http")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=2.0)
