"""Deployment autoscaling (the Ray Serve autoscaler role).

Serve's controller scales replica counts from queue-length metrics
(`python/ray/serve/autoscaling_policy.py` — target in-flight requests
per replica with upper/lower bounds). Same policy here over
:meth:`Deployment.load`: scale up when in-flight demand exceeds
``target_inflight_per_replica`` × replicas, scale down after sustained
idleness. Deterministic ``tick()`` for tests; ``run()`` for the
controller-loop behavior.

With micro-batching enabled, ``load()`` counts LOGICAL requests —
queued-in-the-batch-queue plus in-flight, a 16-request batch weighing
16 — so the demand signal tracks users, never dispatches: a deployment
absorbing its whole queue into one batch per flush still scales on the
depth of that queue.
"""
from __future__ import annotations

import collections
import math
import threading
from dataclasses import dataclass
from typing import Deque, Dict, Optional

from tosem_tpu.serve.core import Serve


@dataclass
class ServeScaleConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    target_inflight_per_replica: float = 2.0
    idle_ticks_before_downscale: int = 3
    max_up_per_tick: int = 2


class ServeAutoscaler:
    def __init__(self, serve: Serve,
                 configs: Optional[Dict[str, ServeScaleConfig]] = None,
                 default: Optional[ServeScaleConfig] = None):
        self.serve = serve
        self.configs = dict(configs or {})
        self.default = default or ServeScaleConfig()
        self._low: Dict[str, int] = {}      # consecutive want-lower ticks
        self.history: Deque[Dict[str, int]] = collections.deque(
            maxlen=1000)                    # bounded: run() is long-lived
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _cfg(self, name: str) -> ServeScaleConfig:
        return self.configs.get(name, self.default)

    def tick(self) -> list:
        decisions = []
        for name, dep in self.serve.deployments().items():
            cfg = self._cfg(name)
            load = dep.load()
            n = dep.num_replicas
            # target replica count from demand (the autoscaling_policy
            # shape): enough replicas for target in-flight each
            desired = max(cfg.min_replicas,
                          min(cfg.max_replicas, math.ceil(
                              load / cfg.target_inflight_per_replica)))
            want = n
            if desired > n:
                self._low[name] = 0
                want = min(n + cfg.max_up_per_tick, desired)
            elif desired < n:
                # hysteresis: shrink one step only after the demand has
                # stayed below the current size for consecutive ticks —
                # a trickle of traffic still scales down toward desired
                self._low[name] = self._low.get(name, 0) + 1
                if self._low[name] >= cfg.idle_ticks_before_downscale:
                    want = n - 1
                    self._low[name] = 0
            else:
                self._low[name] = 0
            if want != n:
                dep.scale(want)
            d = {"deployment": name, "load": load, "replicas": n,
                 "new_replicas": want}
            decisions.append(d)
            self.history.append(d)
        return decisions

    def run(self, interval: float = 1.0) -> None:
        def loop():
            import sys
            warned = set()
            while not self._stop.wait(interval):
                try:
                    self.tick()
                except Exception as e:
                    # keep the controller alive through teardown races,
                    # but surface genuine bugs once per error type —
                    # silently-disabled autoscaling is invisible
                    key = type(e).__name__
                    if key not in warned:
                        warned.add(key)
                        print(f"[serve-autoscaler] tick failed: {e!r}",
                              file=sys.stderr)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="serve-autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
