"""Deployment autoscaling (the Ray Serve autoscaler role).

Serve's controller scales replica counts from queue-length metrics
(`python/ray/serve/autoscaling_policy.py` — target in-flight requests
per replica with upper/lower bounds). Same policy here over
:meth:`Deployment.load` — since the control-plane PR the policy *law*
itself (target backlog, idle-tick hysteresis, bounded step-up) lives
once in :class:`tosem_tpu.control.policy.PolicyCore`; this module is
the thin Serve adapter over it. Deterministic ``tick()`` for tests;
``run()`` for the controller-loop behavior — both unchanged in
semantics from the pre-dedup implementation.

With micro-batching enabled, ``load()`` counts LOGICAL requests —
queued-in-the-batch-queue plus in-flight, a 16-request batch weighing
16 — so the demand signal tracks users, never dispatches: a deployment
absorbing its whole queue into one batch per flush still scales on the
depth of that queue.
"""
from __future__ import annotations

import collections
from dataclasses import dataclass
from typing import Deque, Dict, Optional

from tosem_tpu.control.policy import PolicyCore, ScalePolicy, ScalerLoop
from tosem_tpu.serve.core import Serve


@dataclass
class ServeScaleConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    target_inflight_per_replica: float = 2.0
    idle_ticks_before_downscale: int = 3
    max_up_per_tick: int = 2

    def to_policy(self) -> ScalePolicy:
        """The shared-core translation (proportional mode: trickle
        traffic below target still scales down toward desired)."""
        return ScalePolicy(
            min_units=self.min_replicas, max_units=self.max_replicas,
            target_per_unit=self.target_inflight_per_replica,
            idle_ticks_before_downscale=self.idle_ticks_before_downscale,
            max_up_per_tick=self.max_up_per_tick, mode="proportional")


class ServeAutoscaler(ScalerLoop):
    thread_name = "serve-autoscaler"

    def __init__(self, serve: Serve,
                 configs: Optional[Dict[str, ServeScaleConfig]] = None,
                 default: Optional[ServeScaleConfig] = None):
        super().__init__()
        self.serve = serve
        self.configs = dict(configs or {})
        self.default = default or ServeScaleConfig()
        self._cores: Dict[str, PolicyCore] = {}
        self.history: Deque[Dict[str, int]] = collections.deque(
            maxlen=1000)                    # bounded: run() is long-lived

    def _cfg(self, name: str) -> ServeScaleConfig:
        return self.configs.get(name, self.default)

    def _core(self, name: str) -> PolicyCore:
        """Rebuilt when the deployment's config changed — the pre-dedup
        tick() re-read configs every round, so a live edit of
        ``self.configs`` must keep taking effect (rebuilding resets the
        idle-tick hysteresis, which a changed policy invalidates)."""
        policy = self._cfg(name).to_policy()
        core = self._cores.get(name)
        if core is None or core.policy != policy:
            core = self._cores[name] = PolicyCore(policy)
        return core

    def tick(self) -> list:
        decisions = []
        for name, dep in self.serve.deployments().items():
            load = dep.load()
            n = dep.num_replicas
            want = self._core(name).decide(n, load)
            if want != n:
                dep.scale(want)
            d = {"deployment": name, "load": load, "replicas": n,
                 "new_replicas": want}
            decisions.append(d)
            self.history.append(d)
        return decisions
