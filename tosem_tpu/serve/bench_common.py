"""Shared harness for the serving microbench suites.

The serve, decode, and cluster benches all follow the bench-noise
protocol for the bimodal shared CI hosts: interleaved A/B rounds (both
arms of a round see the same host phase), per-round rates recorded so
``--save`` can floor the baseline at the min across rounds, and the
speedup ratio computed in-round (phase-immune). The closed-loop client
fleet and the row/release-line emission were copy-pasted between
``bench_serve.py`` and ``bench_decode.py``; this module is the single
copy both (and ``bench_cluster.py``) now ride.
"""
from __future__ import annotations

import statistics
import threading
import time
from typing import Any, Callable, List, Optional

from tosem_tpu.utils.results import ResultRow


def closed_loop(call: Callable[..., Any], n_clients: int, min_s: float,
                make_request: Callable[[int, int], Any],
                count_of: Optional[Callable[[Any], float]] = None,
                timeout: float = 120.0,
                samples: Optional[List[tuple]] = None) -> float:
    """``n_clients`` threads calling ``call(request, timeout=...)`` in a
    loop for >= ``min_s`` → completed units per second.

    ``make_request(client_idx, iteration)`` builds each call's payload
    (fixed-per-client fleets ignore ``iteration``; the decode fleet
    cycles prompts with it). ``count_of(response)`` weighs a completed
    call (default 1.0; the token fleets count generated tokens). The
    first client error aborts the measurement and is re-raised — a
    bench must never average over silent failures. ``samples``, when
    given, collects one ``(latency_s, units)`` tuple per completed call
    — the raw material for per-unit latency percentiles (p50/p99
    per-token rows)."""
    stop = time.perf_counter() + min_s
    counts = [0.0] * n_clients
    errors: List[BaseException] = []
    lock = threading.Lock()

    def client(i: int) -> None:
        k = 0
        try:
            while time.perf_counter() < stop:
                c0 = time.perf_counter()
                out = call(make_request(i, k), timeout=timeout)
                dt = time.perf_counter() - c0
                units = count_of(out) if count_of is not None else 1.0
                counts[i] += units
                if samples is not None:
                    with lock:
                        samples.append((dt, units))
                k += 1
        except BaseException as e:   # pragma: no cover - surfaced below
            errors.append(e)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return sum(counts) / (time.perf_counter() - t0)


def per_unit_percentiles(samples: List[tuple],
                         pcts=(50, 99)) -> List[float]:
    """Per-unit latencies (call latency / units completed by that call)
    → the requested percentiles, in ms. A decode call that generated 32
    tokens contributes ONE sample of its per-token cost — the caller-
    visible amortized latency, not a fabricated per-token timeline."""
    per_unit = sorted(dt / max(u, 1.0) for dt, u in samples)
    if not per_unit:
        return [float("nan")] * len(pcts)
    out = []
    for p in pcts:
        idx = min(int(len(per_unit) * p / 100.0), len(per_unit) - 1)
        out.append(per_unit[idx] * 1e3)
    return out


def paired_loop(call_a: Callable[..., Any], call_b: Callable[..., Any],
                n_each: int, min_s: float,
                make_request: Callable[[int, int], Any],
                timeout: float = 120.0) -> "tuple[float, float]":
    """Two closed-loop fleets run CONCURRENTLY over the same wall-clock
    window → (rate_a, rate_b). The strongest phase control this host
    allows: both arms see the same milliseconds, so a host-phase flip
    or GIL convoy hits them together — the ratio is a relative-capacity
    measurement, not a which-window-was-slow lottery. (Sequential A/B
    windows measure the phase; see the failover leg's history.)"""
    stop = time.perf_counter() + min_s
    counts = [0.0, 0.0]
    lock = threading.Lock()
    errors: List[BaseException] = []

    def client(arm: int, call, i: int) -> None:
        c, k = 0, 0
        try:
            while time.perf_counter() < stop:
                call(make_request(i, k), timeout=timeout)
                c += 1
                k += 1
        except BaseException as e:   # pragma: no cover - surfaced below
            errors.append(e)
        with lock:
            counts[arm] += c

    threads = ([threading.Thread(target=client, args=(0, call_a, i))
                for i in range(n_each)]
               + [threading.Thread(target=client, args=(1, call_b, i))
                  for i in range(n_each)])
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    dt = time.perf_counter() - t0
    return counts[0] / dt, counts[1] / dt


class SuiteEmitter:
    """Row/release-line emission for one bench suite: the ``want``
    subset filter, the mean±sd row with per-round minima in ``extra``
    (what ``--save`` floors baselines at), and the quiet-mode line
    buffer."""

    def __init__(self, suite: str, only: Optional[set] = None):
        self.suite = suite
        self.only = only
        self.rows: List[ResultRow] = []
        self.lines: List[str] = []

    def want(self, bench_id: str) -> bool:
        return self.only is None or bench_id in self.only

    def record(self, bench_id: str, name: str, mean: float, sd: float,
               unit: str = "ops/s") -> ResultRow:
        from tosem_tpu.runtime.bench_runtime import _record
        _record(self.rows, self.lines, bench_id, name, mean, sd, unit=unit)
        self.rows[-1].extra["suite"] = self.suite
        return self.rows[-1]

    def emit(self, bench_id: str, name: str, vals: List[float],
             unit: str = "ops/s",
             lower_is_better: bool = False) -> Optional[ResultRow]:
        """Per-round values → one row carrying mean, sd, rounds, and
        the conservative floor (min of rounds for throughput rows, MAX
        for ``lower_is_better`` latency rows — ``--save`` reads
        ``extra["min"]`` as the baseline value either way, and the gate
        inverts its direction off ``extra["lower_is_better"]``).
        Skipped (None) when filtered out or empty."""
        if not self.want(bench_id) or not vals:
            return None
        m = statistics.mean(vals)
        sd = statistics.stdev(vals) if len(vals) > 1 else 0.0
        row = self.record(bench_id, name, m, sd, unit=unit)
        row.extra["rounds"] = [round(v, 4) for v in vals]
        floor = max(vals) if lower_is_better else min(vals)
        row.extra["min"] = round(floor, 4)
        if lower_is_better:
            row.extra["lower_is_better"] = True
        return row

    def flush(self, quiet: bool) -> List[ResultRow]:
        if not quiet:
            for ln in self.lines:
                print(ln)
        return self.rows
