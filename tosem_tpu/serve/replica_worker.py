"""Serve replica process — one backend behind its own RPC server.

The reference's replicas are actor processes the router talks to
directly (``serve/backend_worker.py``); the cluster serving plane keeps
that shape: a node agent spawns this worker (one process per replica,
``cluster/node.py:start_replica``), it instantiates the backend named
by ``--backend module:qualname`` and serves ``call``/``call_batch``/
``warmup``/``load``/``stats`` over :class:`~tosem_tpu.cluster.rpc.RpcServer`.
The router tier holds a client per replica address — requests never
bounce through the agent.

Two wire details the router relies on:

- ``call`` returns ``{"value": ..., "load": n}`` — the replica's
  in-flight depth rides every response, so the router's queue-depth
  view refreshes for free instead of paying a scrape RPC per request
  (the bench-noise rule: no per-step remote scrapes).
- A backend exception travels as an ``RpcError`` (application error:
  never retried, counted against the breaker); a dead replica surfaces
  as ``ConnectionError`` (retried on a surviving replica).

Import discipline: this module must not import jax or numpy — cheap
backends (echo, bench synthetics) boot in well under a second, and a
sharded backend's jax import happens AFTER ``--devices`` has pinned
``XLA_FLAGS`` in the environment (the agent sets it pre-spawn).
"""
from __future__ import annotations

import importlib
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

# import-light on purpose (os + threading only) — the fencing watermark
# must be importable here without dragging in jax/numpy
from tosem_tpu.cluster.fencing import Watermark


def resolve_backend(ref: str):
    """``"module:qualname"`` → class/factory (the trainable_ref idiom
    of the trial plane, reused so one addressing scheme names every
    code object that ships to another process)."""
    mod_name, _, qual = ref.partition(":")
    if not mod_name or not qual:
        raise ValueError(f"backend ref {ref!r} is not 'module:qualname'")
    obj = importlib.import_module(mod_name)
    for part in qual.split("."):
        obj = getattr(obj, part)
    return obj


class ReplicaHandlers:
    """RPC surface of one replica (the backend_worker role)."""

    def __init__(self, backend: Any):
        self._backend = backend
        self._lock = threading.Lock()
        self._inflight = 0
        self._served = 0
        self._errors = 0
        self._started = time.time()
        # epoch watermark: control-plane writes stamped with an older
        # head epoch than the highest this replica has seen are rejected
        # typed (StaleEpochError) — a superseded head cannot double-
        # adopt KV state or stop a replica the new head owns
        self._epoch = Watermark()
        # throttled prefix-digest snapshot piggybacked on responses
        # (routers learn which prefixes live here without extra RPCs)
        self._digest = None
        self._digest_ts = 0.0

    _DIGEST_TTL_S = 0.25

    def _prefix_digest(self):
        fn = getattr(self._backend, "prefix_digest", None)
        if not callable(fn):
            return None
        now = time.monotonic()
        if now - self._digest_ts > self._DIGEST_TTL_S:
            try:
                self._digest = fn()
            except Exception:
                self._digest = None
            self._digest_ts = now
        return self._digest

    def _enter(self) -> None:
        with self._lock:
            self._inflight += 1

    def _leave(self, ok: bool) -> int:
        with self._lock:
            self._inflight -= 1
            self._served += 1
            if not ok:
                self._errors += 1
            return self._inflight

    def call(self, request: Any) -> Dict[str, Any]:
        self._enter()
        ok = False
        try:
            value = self._backend.call(request)
            ok = True
        finally:
            depth = self._leave(ok)
        out = {"value": value, "load": depth}
        digest = self._prefix_digest()
        if digest:
            out["prefixes"] = digest
        return out

    def call_batch(self, requests: List[Any],
                   bucket: Optional[int] = None) -> Dict[str, Any]:
        self._enter()
        ok = False
        try:
            values = self._backend.call_batch(requests, bucket)
            ok = True
        finally:
            depth = self._leave(ok)
        return {"value": values, "load": depth}

    def warmup(self, shapes: List[Any]) -> Any:
        if hasattr(self._backend, "warmup"):
            return self._backend.warmup(shapes)
        return {"warmed": 0}

    def backend_call(self, method: str, *args: Any,
                     **kwargs: Any) -> Any:
        """Forward a control-plane call to a PUBLIC backend method —
        the decode-migration surface (``list_seqs`` /
        ``transport_address`` / ``send_seq`` / ``adopt_seq`` /
        ``export_seq`` / ``import_seq``) without widening the fixed
        data-plane RPC vocabulary. Only the tiny control messages ride
        this path; migrated page bytes stream replica→replica over
        :mod:`tosem_tpu.cluster.transport` (no driver hop).

        The reserved ``_epoch`` kwarg (never forwarded to the backend)
        is the caller head's fencing epoch: a value below this
        replica's watermark raises
        :class:`~tosem_tpu.cluster.fencing.StaleEpochError` instead of
        mutating state — the fence that makes a superseded head's
        ``adopt_seq`` a typed no-op rather than a double adoption."""
        epoch = kwargs.pop("_epoch", None)
        self._epoch.check(epoch, what=f"backend_call:{method}")
        if method.startswith("_"):
            raise ValueError(f"backend method {method!r} is private")
        fn = getattr(self._backend, method, None)
        if not callable(fn):
            raise KeyError(f"backend has no method {method!r}")
        return fn(*args, **kwargs)

    def fence(self, epoch: int) -> int:
        """Advance the replica's epoch watermark (a recovered head
        fences the replicas it re-adopts). Monotonic: fencing to an
        OLDER epoch raises — the new head cannot be fenced out by a
        delayed call from the superseded one."""
        self._epoch.check(int(epoch), what="fence")
        return self._epoch.epoch

    def load(self) -> int:
        with self._lock:
            return self._inflight

    def health(self) -> Dict[str, Any]:
        return {"ok": True, "pid": os.getpid(),
                "uptime_s": time.time() - self._started}

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {"pid": os.getpid(), "inflight": self._inflight,
                   "served": self._served, "errors": self._errors}
        if hasattr(self._backend, "stats"):
            try:
                backend_stats = self._backend.stats()
                if isinstance(backend_stats, dict):
                    out.update(backend_stats)
            except Exception:
                pass          # telemetry must never fail the data plane
        return out


def serve_replica(backend_ref: str, init_kwargs: Dict[str, Any],
                  port: int = 0, announce_fd: Optional[int] = None,
                  lifeline_fd: Optional[int] = None) -> None:
    """Run one replica until killed, or until the lifeline pipe hits
    EOF — the write end lives in the spawning agent, so the replica
    dies WITH its agent however the agent goes (SIGKILL included; a
    dead node must not leave orphan replicas answering on old ports —
    PDEATHSIG is not deliverable on every kernel this runs under)."""
    from tosem_tpu.cluster.rpc import RpcServer
    # mark this process as a DEDICATED replica: compile-cache model
    # pins taken here (CompiledBackendMixin.warmup) live exactly as
    # long as the replica — in shared processes (driver, actor
    # workers) backends must NOT pin, or deployment churn would pin
    # the budgeted cache over its bound forever
    os.environ["TOSEM_REPLICA_PROCESS"] = "1"
    backend = resolve_backend(backend_ref)(**init_kwargs)
    server = RpcServer(ReplicaHandlers(backend), port=port)
    line = f"{server.address}\n".encode()
    if announce_fd is not None:
        os.write(announce_fd, line)
        os.close(announce_fd)
    else:
        sys.stdout.write(line.decode())
        sys.stdout.flush()
    try:
        if lifeline_fd is not None:
            while os.read(lifeline_fd, 1):
                pass             # nothing is ever written; EOF = parent died
        else:
            while True:
                time.sleep(3600)
    except (KeyboardInterrupt, OSError):
        pass
    finally:
        server.shutdown()


def main(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    backend_ref, kwargs_json, port = "", "{}", 0
    announce_fd, lifeline_fd = None, None
    i = 0
    while i < len(args):
        if args[i] == "--backend":
            backend_ref = args[i + 1]; i += 2
        elif args[i] == "--init-kwargs":
            kwargs_json = args[i + 1]; i += 2
        elif args[i] == "--port":
            port = int(args[i + 1]); i += 2
        elif args[i] == "--announce-fd":
            announce_fd = int(args[i + 1]); i += 2
        elif args[i] == "--lifeline-fd":
            lifeline_fd = int(args[i + 1]); i += 2
        else:
            print(f"unknown arg {args[i]}", file=sys.stderr)
            return 2
    if not backend_ref:
        print("--backend module:qualname is required", file=sys.stderr)
        return 2
    serve_replica(backend_ref, json.loads(kwargs_json), port=port,
                  announce_fd=announce_fd, lifeline_fd=lifeline_fd)
    return 0


if __name__ == "__main__":
    sys.exit(main())
