"""Serving data-plane microbenchmarks (the serve analog of
``runtime/bench_runtime.py``).

Measures the micro-batching fast path end to end: closed-loop client
fleets against the SAME backend deployed unbatched vs batched —
interleaved A/B rounds in one process per the bench-noise protocol
(single runs are meaningless on shared 2-CPU CI hosts; alternating
rounds see the same machine phases) — plus an open-loop arrival leg and
a warm-vs-cold first-request probe of the deploy-time compile cache.

``python -m tosem_tpu.cli microbench --serve`` runs it; ``--save`` /
``--check`` record and gate against a baseline JSON exactly like the
runtime benches (``ci.sh --perf`` gates on
``results/bench_serve.json`` floors — record floors as the min across
rounds spanning fast AND slow host phases).
"""
from __future__ import annotations

import time
from typing import List, Optional

from tosem_tpu.serve.bench_common import SuiteEmitter, closed_loop
from tosem_tpu.utils.results import ResultRow

# Gated by ci.sh --perf (higher-is-better throughput + the batched/
# unbatched speedup ratio, which is phase-immune because both sides of
# a round share the host phase). The BERT b8_t512 legs are NOT gated:
# they carry model-compile cost that would blow the perf tier's budget
# — they run in the full bench (bench.py serve_bench leg) instead.
GATED_SERVE_BENCHES = (
    "serve_single_closed_loop", "serve_unbatched_c16", "serve_batched_c16",
    "serve_batch_speedup",
)

DEFAULT_BASELINE = "results/bench_serve.json"


class VectorWorkBackend:
    """Synthetic inference backend: a few chained matvecs per request,
    one chained matmul per batch — realistic per-item device work whose
    vectorized batch path amortizes both the actor round trip and the
    per-call overhead, without model-framework noise."""

    ITERS = 4

    def __init__(self, n: int = 256):
        import numpy as np
        self._w = (np.random.default_rng(0)
                   .normal(size=(n, n)).astype(np.float32) / n)

    def call(self, request):
        import numpy as np
        x = np.full((self._w.shape[0],), float(request["x"]), np.float32)
        for _ in range(self.ITERS):
            x = self._w @ x
        return float(x[0])

    def call_batch(self, requests, pad_to=None):
        import numpy as np
        X = np.stack([np.full((self._w.shape[0],), float(r["x"]),
                              np.float32) for r in requests], axis=1)
        for _ in range(self.ITERS):
            X = self._w @ X
        return [float(v) for v in X[0]]


def _closed_loop(handle, n_clients: int, min_s: float,
                 make_request=None) -> float:
    """``n_clients`` threads in a call loop for >= min_s → ops/s.
    ``make_request(client_idx)`` builds each client's (fixed) payload;
    defaults to the synthetic backend's ``{"x": i}``. (Thin wrapper
    over the shared fleet in :mod:`tosem_tpu.serve.bench_common`.)"""
    mk = make_request or (lambda i: {"x": i})
    return closed_loop(handle.call, n_clients, min_s,
                       lambda i, k: mk(i), timeout=60.0)


def _open_loop(handle, rate: float, duration_s: float) -> float:
    """Open-loop arrivals at ``rate``/s (requests fired on a clock, not
    on completion — the arrival model real traffic follows); returns
    completed/s. A data plane that keeps up completes ≈ rate."""
    futs = []
    t0 = time.perf_counter()
    n = max(1, int(rate * duration_s))
    for i in range(n):
        target = t0 + i / rate
        now = time.perf_counter()
        if target > now:
            time.sleep(target - now)
        futs.append(handle.remote({"x": i}))
    for f in futs:
        f.result(timeout=60.0)
    return n / (time.perf_counter() - t0)


def run_serve_benchmarks(trials: int = 3, min_s: float = 0.5,
                         quiet: bool = False,
                         only: Optional[set] = None,
                         skip_warm: bool = False) -> List[ResultRow]:
    """Interleaved A/B serve benches; ``only`` restricts bench_ids."""
    import tosem_tpu.runtime as rt
    from tosem_tpu.serve.core import Serve

    em = SuiteEmitter("serve", only)
    want, record, emit = em.want, em.record, em.emit

    own_runtime = not rt.is_initialized()
    if own_runtime:
        rt.init(num_workers=2, memory_monitor=False)

    serve = Serve()
    un = serve.deploy("bench-unbatched", VectorWorkBackend,
                      num_replicas=1, max_retries=1)
    ba = serve.deploy("bench-batched", VectorWorkBackend,
                      num_replicas=1, max_retries=1,
                      max_batch_size=16, batch_wait_ms=3.0)
    h_un, h_ba = serve.get_handle("bench-unbatched"), \
        serve.get_handle("bench-batched")
    h_un.call({"x": 0}, timeout=120.0)     # cold-boot both replicas
    h_ba.call({"x": 0}, timeout=120.0)

    throughput_ids = {"serve_single_closed_loop", "serve_single_unbatched",
                      "serve_single_latency_ratio", "serve_unbatched_c16",
                      "serve_batched_c16", "serve_batch_speedup",
                      "serve_open_loop_c16"}
    if only is None or throughput_ids & only:
        single, single_un, lat_ratio = [], [], []
        unb, bat, ratios, open_tp = [], [], [], []
        for _ in range(max(trials, 1)):
            # one A/B round: every leg sees the same host phase
            s_b = _closed_loop(h_ba, 1, min_s)
            s_u = _closed_loop(h_un, 1, min_s)
            single.append(s_b)
            single_un.append(s_u)
            # single-client closed-loop throughput == 1/latency, so this
            # ratio >= 1/1.2 is the "batching costs an idle client <=
            # 1.2x p50" acceptance criterion, phase-immune in-round
            lat_ratio.append(s_b / s_u if s_u else float("inf"))
            a = _closed_loop(h_un, 16, min_s)
            b = _closed_loop(h_ba, 16, min_s)
            unb.append(a)
            bat.append(b)
            ratios.append(b / a if a else float("inf"))
            if want("serve_open_loop_c16"):
                open_tp.append(_open_loop(h_ba, rate=1.5 * a,
                                          duration_s=min_s))

        emit("serve_single_closed_loop",
             "serve single client closed loop", single)
        emit("serve_single_unbatched",
             "serve single client unbatched", single_un)
        emit("serve_single_latency_ratio",
             "serve single client batched vs unbatched", lat_ratio,
             unit="x")
        emit("serve_unbatched_c16", "serve 16 clients unbatched", unb)
        emit("serve_batched_c16", "serve 16 clients batched", bat)
        emit("serve_batch_speedup", "serve batched vs unbatched speedup",
             ratios, unit="x")
        emit("serve_open_loop_c16", "serve open loop arrivals", open_tp)

    serve.delete("bench-unbatched")
    serve.delete("bench-batched")

    # north-star-shaped leg: tiny-topology BERT at the b8_t512 bucket,
    # padded variable-length requests on the flash kernels. Unbatched
    # serves each request through the SAME max_batch-padded program
    # (bit-exact contract), so the A/B isolates exactly what batching
    # buys: 8 requests per program call instead of 1. Both deployments
    # pre-warm the bucket so compile time stays out of the loops.
    bert_ids = {"serve_bert_unbatched_c16", "serve_bert_batched_c16",
                "serve_bert_batch_speedup"}
    if only is None or bert_ids & only:
        from tosem_tpu.serve.backends import BertEncodeBackend
        kw = dict(num_replicas=1, max_retries=1,
                  init_kwargs={"max_len": 512, "max_batch": 8})
        # the unbatched arm pads per request (128/256/384/512 for the
        # 65..504 client lengths): warm ALL of them so no cold compile
        # lands inside its timed loop and inflates the A/B ratio —
        # the batched arm only ever runs the 512 bucket
        serve.deploy("bench-bert-un", BertEncodeBackend,
                     warmup_shapes=[128, 256, 384, 512], **kw)
        ba_dep = serve.deploy("bench-bert-ba", BertEncodeBackend,
                              max_batch_size=8, batch_wait_ms=10.0,
                              buckets=[512],
                              length_of=BertEncodeBackend.length_of,
                              warmup_shapes=[512], **kw)
        hb_un = serve.get_handle("bench-bert-un")
        hb_ba = serve.get_handle("bench-bert-ba")
        # fixed per-client variable lengths: every batch mixes lengths,
        # so the padding-bucket router and key-padding masks do real work
        # variable lengths (65..504), ids wrapped into the tiny vocab
        mk = lambda i: {"ids": [1 + (j % 126)
                                for j in range(1 + 64 + (i * 53) % 440)]}
        hb_un.call(mk(0), timeout=300.0)
        hb_ba.call(mk(0), timeout=300.0)
        bmin_s = max(min_s, 2.0)     # ~240ms/program on slow hosts
        b_unb, b_bat, b_ratio = [], [], []
        for _ in range(max(trials, 1)):
            a = _closed_loop(hb_un, 16, bmin_s, make_request=mk)
            b = _closed_loop(hb_ba, 16, bmin_s, make_request=mk)
            b_unb.append(a)
            b_bat.append(b)
            b_ratio.append(b / a if a else float("inf"))
        emit("serve_bert_unbatched_c16",
             "serve bert b8_t512 16 clients unbatched", b_unb)
        emit("serve_bert_batched_c16",
             "serve bert b8_t512 16 clients batched", b_bat)
        row = emit("serve_bert_batch_speedup",
                   "serve bert b8_t512 batch speedup", b_ratio, unit="x")
        # the flash-path proof: the replica's trace-time dispatch tally
        # must show only flash programs (padded batches that fell off
        # the fused path would count under "xla")
        disp = rt.get(ba_dep._replicas[0].stats.remote(),
                      timeout=60.0)["flash_dispatch"]
        if disp.get("xla", 0) or not disp.get("flash", 0):
            raise RuntimeError(
                f"bert serve batches not on the flash path: {disp}")
        if row is not None:
            row.extra["flash_dispatch"] = dict(disp)
        serve.delete("bench-bert-un")
        serve.delete("bench-bert-ba")

    # warm-vs-cold first request: the compile-cache acceptance probe.
    # Not gated (absolute compile seconds swing with host phase); the
    # RATIO is the criterion — a pre-warmed deployment's first request
    # must not pay the JIT.
    if not skip_warm and want("serve_warm_first_request"):
        from tosem_tpu.serve.backends import BertEncodeBackend
        cold = serve.deploy("bench-cold", BertEncodeBackend,
                            num_replicas=1, max_batch_size=8,
                            buckets=[128],
                            length_of=BertEncodeBackend.length_of)
        t0 = time.perf_counter()
        serve.get_handle("bench-cold").call({"ids": [1, 2, 3]},
                                            timeout=300.0)
        cold_ms = (time.perf_counter() - t0) * 1e3
        serve.delete("bench-cold")
        warm = serve.deploy("bench-warm", BertEncodeBackend,
                            num_replicas=1, max_batch_size=8,
                            buckets=[128],
                            length_of=BertEncodeBackend.length_of,
                            warmup_shapes=[128])
        t0 = time.perf_counter()
        serve.get_handle("bench-warm").call({"ids": [1, 2, 3]},
                                            timeout=300.0)
        warm_ms = (time.perf_counter() - t0) * 1e3
        serve.delete("bench-warm")
        row = record("serve_warm_first_request",
                     "serve warm vs cold first request",
                     cold_ms / warm_ms, 0.0, unit="x")
        row.extra.update({"cold_ms": round(cold_ms, 1),
                          "warm_ms": round(warm_ms, 1)})

    if own_runtime:
        rt.shutdown()
    return em.flush(quiet)
