"""Cyber-style component model: fused readers + timer components.

The reference's component runtime (`cyber/component/component.h:58-136`
``Component<M0..M3>`` — proc fires on the primary channel's message with
the latest fused message from each secondary channel;
`timer_component.h` — periodic proc; channels resolved through topology
discovery). TPU-era translation: a **deterministic virtual-time event
loop** instead of croutine scheduling — messages and timer firings are
(time, seq) events in one priority queue, so pipelines replay exactly
(the property record/replay and CI need), while heavy math inside a
``proc`` stays jitted JAX like everywhere else in the framework.

Channels register in the cluster :class:`~tosem_tpu.cluster.discovery.
Registry` (kind ``"channel"``), so writers/readers are discoverable the
way Cyber's topology manager exposes them.
"""
from __future__ import annotations

import collections
import heapq
import itertools
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from tosem_tpu.cluster.discovery import Registry


class Component:
    """Fused-reader component (``Component<M0, M1...>`` analog).

    Subclass and override :meth:`proc`. The first declared channel is the
    primary: ``proc`` runs once per primary message, receiving the
    message plus the *latest* message seen on each secondary channel
    (``None`` until one arrives) — Apollo's fusion semantics.
    """

    def __init__(self, name: str, channels: Sequence[str]):
        if not channels:
            raise ValueError("component needs at least one channel")
        self.name = name
        self.channels = list(channels)

    def on_init(self, ctx: "ComponentContext") -> None:
        pass

    def proc(self, primary: Any, *fused: Any) -> None:
        raise NotImplementedError


class CoroutineComponent:
    """Cooperative multi-step task — the croutine role, deterministic.

    The reference schedules userspace coroutines that yield at blocking
    points (``cyber/croutine/croutine.h``: ``data_wait`` parks the
    routine until its reader has data, the scheduler resumes it). TPU
    collapse: :meth:`run` is a **generator**; every ``yield "channel"``
    parks the routine until the next message on that channel arrives
    (delivered as the value of the yield), and ``yield ("sleep", dt)``
    parks it for virtual time. Cooperative scheduling on the same
    deterministic (time, seq) event loop — no OS threads, fully
    replayable, which is what croutines buy Apollo minus the context-
    switch machinery XLA's async dispatch already makes unnecessary.

    Subclass and override :meth:`run`; it is started at ``add()`` time
    and retired when the generator returns.
    """

    def __init__(self, name: str):
        self.name = name

    def on_init(self, ctx: "ComponentContext") -> None:
        pass

    def run(self, ctx: "ComponentContext"):
        raise NotImplementedError
        yield  # pragma: no cover  (marks this as a generator template)


class TimerComponent:
    """Periodic component (``timer_component.h`` analog)."""

    def __init__(self, name: str, interval: float):
        if interval <= 0:
            raise ValueError("interval must be positive")
        self.name = name
        self.interval = float(interval)

    def on_init(self, ctx: "ComponentContext") -> None:
        pass

    def proc(self) -> None:
        raise NotImplementedError


@dataclass(frozen=True)
class ChannelQos:
    """Per-channel QoS profile (cyber transport's QosProfile: history
    depth + reliability tier).

    ``reliability="reliable"`` delivers every message; ``"best_effort"``
    keeps at most ``depth`` undelivered messages per channel (KEEP_LAST:
    under write pressure the OLDEST pending message is dropped, the
    sensor-stream semantics — a fresher lidar frame supersedes a stale
    one). ``depth`` also sizes the reader-side history buffer
    (:meth:`ComponentRuntime.history`)."""
    depth: int = 1
    reliability: str = "reliable"

    def __post_init__(self):
        if self.depth < 1:
            raise ValueError("qos depth must be >= 1")
        if self.reliability not in ("reliable", "best_effort"):
            raise ValueError(f"unknown reliability {self.reliability!r}")


_DEFAULT_QOS = ChannelQos()


@dataclass
class ComponentContext:
    """Handed to components at init: write access + the current clock."""
    runtime: "ComponentRuntime"

    def writer(self, channel: str,
               qos: Optional[ChannelQos] = None) -> Callable[[Any], None]:
        return self.runtime.writer(channel, owner="component", qos=qos)

    @property
    def now(self) -> float:
        return self.runtime.now


class ComponentRuntime:
    """Deterministic single-process component host.

    Events (message deliveries, timer firings) execute in (time, seq)
    order on a virtual clock; :meth:`run_until` advances it. Writers
    enqueue at the current virtual time plus an optional latency.
    """

    def __init__(self, registry: Optional[Registry] = None):
        self.registry = registry or Registry()
        self.now = 0.0
        self._seq = itertools.count()
        self._events: List[Tuple[float, int, Callable[[], None]]] = []
        self._components: List[Any] = []
        self._latest: Dict[str, Any] = {}          # channel -> last message
        self._subs: Dict[str, List[Component]] = {}
        self._stats: Dict[str, int] = {}
        self._qos: Dict[str, ChannelQos] = {}
        self._pending: Dict[str, Any] = {}         # best-effort queues
        self._history: Dict[str, Any] = {}         # channel -> deque
        self._drops: Dict[str, int] = {}
        self._waiters: Dict[str, List[Any]] = {}   # croutine data_wait

    # ------------------------------------------------------- channels

    def set_qos(self, channel: str, qos: ChannelQos) -> None:
        """Pin a channel's QoS profile (cyber's reader/writer QosProfile;
        here per-channel, single-controller collapse)."""
        self._qos[channel] = qos

    def qos(self, channel: str) -> ChannelQos:
        return self._qos.get(channel, _DEFAULT_QOS)

    def writer(self, channel: str, owner: str = "external",
               qos: Optional[ChannelQos] = None) -> Callable[[Any], None]:
        """Create a channel writer (``node->CreateWriter`` analog);
        registers the channel for discovery."""
        self.registry.register("channel", channel,
                               {"owner": owner}, unique=False)
        if qos is not None:
            self.set_qos(channel, qos)

        def write(message: Any, *, latency: float = 0.0) -> None:
            q = self.qos(channel)
            when = self.now + max(latency, 0.0)
            if q.reliability == "best_effort":
                # KEEP_LAST by write order, but each surviving message
                # still delivers at ITS OWN latency: pending is an
                # insertion-ordered id→message map; a dropped id's event
                # fires into nothing
                pend = self._pending.setdefault(channel, {})
                mid = next(self._seq)
                pend[mid] = message
                while len(pend) > q.depth:    # drop the oldest-written
                    pend.pop(next(iter(pend)))
                    self._drops[channel] = self._drops.get(channel, 0) + 1
                self._push(when,
                           lambda: self._deliver_token(channel, mid))
            else:
                self._push(when, lambda: self._deliver(channel, message))
        return write

    _MISSING = object()

    def _deliver_token(self, channel: str, mid: int) -> None:
        msg = self._pending.get(channel, {}).pop(mid, self._MISSING)
        if msg is not self._MISSING:   # else: superseded before arrival
            self._deliver(channel, msg)

    def history(self, channel: str) -> List[Any]:
        """Last ``qos(channel).depth`` DELIVERED messages, oldest first
        (the reader-side history buffer of a depth-k subscription)."""
        return list(self._history.get(channel, ()))

    def drop_counts(self) -> Dict[str, int]:
        """Messages dropped per best-effort channel (KEEP_LAST policy)."""
        return dict(self._drops)

    def channels(self) -> List[str]:
        return self.registry.list("channel")

    # ----------------------------------------------------- components

    def add(self, comp: Any) -> None:
        if isinstance(comp, CoroutineComponent):
            self._components.append(comp)
            comp.on_init(ComponentContext(self))
            gen = comp.run(ComponentContext(self))
            # first advance runs as a scheduled event so startup order
            # is (time, seq)-deterministic like everything else
            self._push(self.now,
                       lambda: self._advance_coroutine(comp, gen, None))
        elif isinstance(comp, TimerComponent):
            self._components.append(comp)
            comp.on_init(ComponentContext(self))
            self._schedule_timer(comp, self.now + comp.interval)
        elif isinstance(comp, Component):
            self._components.append(comp)
            for ch in comp.channels:
                self.registry.register("channel", ch,
                                       {"owner": "reader"}, unique=False)
                self._subs.setdefault(ch, [])
            self._subs[comp.channels[0]].append(comp)
            comp.on_init(ComponentContext(self))
        else:
            raise TypeError(f"not a component: {comp!r}")

    # ------------------------------------------------------ execution

    def _push(self, t: float, fn: Callable[[], None]) -> None:
        heapq.heappush(self._events, (t, next(self._seq), fn))

    def _schedule_timer(self, comp: TimerComponent, t: float) -> None:
        def fire():
            # reschedule BEFORE proc: a raising proc must not silently
            # unschedule the timer (message components stay subscribed
            # through failures; timers get the same semantics). Stats
            # count successful procs only, matching _deliver.
            self._schedule_timer(comp, t + comp.interval)
            comp.proc()
            self._stats[comp.name] = self._stats.get(comp.name, 0) + 1
        self._push(t, fire)

    def _park(self, comp: "CoroutineComponent", gen, req,
              mail=None) -> None:
        """Park a routine per its yield request (channel / sleep)."""
        if isinstance(req, str):        # data_wait: park with a mailbox
            rec = {"comp": comp, "gen": gen,
                   "mail": mail if mail is not None else
                   collections.deque(), "scheduled": False}
            self._waiters.setdefault(req, []).append(rec)
            if rec["mail"]:             # leftovers: drain immediately
                rec["scheduled"] = True
                self._push(self.now,
                           lambda: self._drain_waiter(req, rec))
        elif (isinstance(req, tuple) and len(req) == 2
                and req[0] == "sleep"):
            self._push(self.now + max(float(req[1]), 0.0),
                       lambda: self._advance_coroutine(comp, gen, None))
        else:
            raise TypeError(
                f"coroutine {comp.name!r} yielded {req!r}; expected a "
                "channel name or ('sleep', seconds)")

    def _advance_coroutine(self, comp: "CoroutineComponent", gen,
                           value: Any) -> None:
        """Resume a parked routine; park it again at its next yield."""
        try:
            req = gen.send(value)
        except StopIteration:
            return                      # routine finished: retire
        self._stats[comp.name] = self._stats.get(comp.name, 0) + 1
        self._park(comp, gen, req)

    def _drain_waiter(self, channel: str, rec) -> None:
        """Feed a parked routine one buffered message. The mailbox makes
        same-timestamp (or resume-in-flight) deliveries lossless: every
        message lands in the waiter's queue at _deliver time and is
        consumed one-per-yield here; leftovers follow the routine if it
        parks on the same channel again, so bursts are never dropped."""
        rec["scheduled"] = False
        if not rec["mail"]:
            return
        msg = rec["mail"].popleft()
        lst = self._waiters.get(channel, [])
        lst.remove(rec)
        if not lst:
            self._waiters.pop(channel, None)
        comp, gen = rec["comp"], rec["gen"]
        try:
            req = gen.send(msg)
        except StopIteration:
            return
        self._stats[comp.name] = self._stats.get(comp.name, 0) + 1
        if req == channel:
            self._park(comp, gen, req, mail=rec["mail"])
        else:
            self._park(comp, gen, req)

    def _deliver(self, channel: str, message: Any) -> None:
        self._latest[channel] = message
        hist = self._history.get(channel)
        depth = self.qos(channel).depth
        if hist is None or hist.maxlen != depth:
            hist = collections.deque(hist or (), maxlen=depth)
            self._history[channel] = hist
        hist.append(message)
        # wake parked routines (data_wait satisfied): the message goes
        # into each waiter's mailbox and the drain runs as a scheduled
        # event, so ordering stays (time, seq) and bursts are lossless
        for rec in list(self._waiters.get(channel, [])):
            rec["mail"].append(message)
            if not rec["scheduled"]:
                rec["scheduled"] = True
                self._push(self.now,
                           lambda r=rec: self._drain_waiter(channel, r))
        for comp in self._subs.get(channel, []):
            fused = [self._latest.get(ch) for ch in comp.channels[1:]]
            comp.proc(message, *fused)
            self._stats[comp.name] = self._stats.get(comp.name, 0) + 1

    def run_until(self, t: float) -> int:
        """Advance the virtual clock to ``t``; returns events executed.
        Timer events beyond ``t`` stay queued for the next call. The
        clock is monotonic: a ``t`` in the past is a caller bug (it
        would let new writes deliver before already-executed events)."""
        if t < self.now:
            raise ValueError(f"run_until({t}) would rewind the clock "
                             f"(now={self.now})")
        executed = 0
        while self._events and self._events[0][0] <= t:
            when, _, fn = heapq.heappop(self._events)
            self.now = when
            fn()
            executed += 1
        self.now = t
        return executed

    def proc_counts(self) -> Dict[str, int]:
        return dict(self._stats)
