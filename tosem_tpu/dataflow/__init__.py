"""Streaming dataflow / component-DAG engine on the actor runtime.

The only reference-present parallelism strategy the framework lacked
(SURVEY §2.7): Apollo Cyber's component model — callbacks wired by typed
channels under a scheduler (``cyber/component/component.h:58-136``) — and
Ray Streaming's stage dataflow with credit-based backpressure
(``streaming/src/data_writer.cc``). Single-controller TPU shape: stages
are runtime actors (stateful, restartable) or stateless task fans; the
driver owns routing, credits, and end-of-stream propagation.
"""
from tosem_tpu.dataflow.components import (ChannelQos, Component,
                                           ComponentContext,
                                           ComponentRuntime,
                                           CoroutineComponent,
                                           TimerComponent)
from tosem_tpu.dataflow.graph import (Stage, StreamGraph, keyed, rebalance,
                                      broadcast)

__all__ = ["StreamGraph", "Stage", "keyed", "rebalance", "broadcast",
           "Component", "TimerComponent", "ComponentRuntime",
           "ComponentContext", "ChannelQos", "CoroutineComponent"]
