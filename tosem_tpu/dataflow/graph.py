"""Stage-dataflow graph: sources → operators → sinks over runtime actors.

Design (vs the reference):

- **Cyber component model** (``cyber/component/component.h:58-136``): a
  component's ``Proc(msg...)`` fires when its input channels have data,
  under a croutine scheduler. Here an operator is a class with
  ``process(item) -> item | list | None`` (None = filtered) instantiated
  as ONE actor per parallel instance — state is explicit and per-instance,
  restarts follow the actor policy.
- **Ray streaming** (``streaming/src/data_writer.cc``): writers push to
  per-channel ring buffers with credit-based backpressure. Here the driver
  is the single controller: it tracks in-flight calls per instance and
  stops pulling from sources when any downstream instance is at its credit
  limit — bounded memory end to end.
- **Partitioning**: ``rebalance`` (round-robin), ``keyed(fn)`` (hash
  partitioning, preserves per-key ordering to ONE instance — the keyBy of
  cyber/ray streaming), ``broadcast`` (every instance sees every item).

End-of-stream: when sources exhaust and a stage's upstreams have fully
drained, the driver calls the operator's optional ``flush()`` on each
instance and forwards its output downstream — watermark propagation
collapsed to the single-controller case.
"""
from __future__ import annotations

import collections
import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import tosem_tpu.runtime as rt


def rebalance():
    return ("rebalance", None)


def keyed(key_fn: Callable[[Any], Any]):
    return ("keyed", key_fn)


def broadcast():
    return ("broadcast", None)


class _FnOperator:
    """Wraps a plain function as a stateless operator."""

    def __init__(self, fn):
        self.fn = fn

    def process(self, item):
        return self.fn(item)


@dataclass
class Stage:
    name: str
    op_factory: Optional[Callable[[], Any]]   # None for sources/sinks
    parallelism: int = 1
    partitioning: Tuple[str, Optional[Callable]] = ("rebalance", None)
    is_source: bool = False
    is_sink: bool = False
    source_iter: Optional[Iterable] = None
    # runtime state
    handles: List[Any] = field(default_factory=list)
    inflight: Dict[int, List[Any]] = field(default_factory=dict)
    rr: Any = None
    upstreams: List["Stage"] = field(default_factory=list)
    downstreams: List["Stage"] = field(default_factory=list)
    closed: bool = False
    flushed: bool = False
    results: List[Any] = field(default_factory=list)


class _OperatorActor:
    """The per-instance actor: owns one operator instance."""

    def __init__(self, factory_blob):
        import cloudpickle
        factory = cloudpickle.loads(factory_blob)
        self.op = factory()

    def process(self, item):
        return self.op.process(item)

    def flush(self):
        f = getattr(self.op, "flush", None)
        return f() if f is not None else None


class StreamGraph:
    """Build + run a stage DAG.

    ::

        g = StreamGraph()
        src = g.source("nums", range(100))
        sq = g.stage("square", lambda x: x * x, parallelism=2)
        agg = g.stage("sum", SumOperator, partitioning=keyed(lambda x: 0))
        out = g.sink("out")
        g.connect(src, sq); g.connect(sq, agg); g.connect(agg, out)
        results = g.run()["out"]
    """

    def __init__(self):
        self.stages: Dict[str, Stage] = {}

    def _add(self, st: Stage) -> Stage:
        if st.name in self.stages:
            raise ValueError(f"duplicate stage {st.name!r}")
        self.stages[st.name] = st
        return st

    def source(self, name: str, iterable: Iterable) -> Stage:
        return self._add(Stage(name, None, is_source=True,
                               source_iter=iter(iterable)))

    def stage(self, name: str, op, parallelism: int = 1,
              partitioning=None) -> Stage:
        """``op``: a callable item→item (stateless) or an operator class
        with ``process``/optional ``flush`` (stateful, one per instance)."""
        import inspect
        if inspect.isclass(op):
            factory = op
        else:
            factory = (lambda f=op: _FnOperator(f))
        return self._add(Stage(name, factory, parallelism=parallelism,
                               partitioning=partitioning or rebalance()))

    def sink(self, name: str) -> Stage:
        return self._add(Stage(name, None, is_sink=True))

    def connect(self, a: Stage, b: Stage) -> None:
        a.downstreams.append(b)
        b.upstreams.append(a)

    # ------------------------------------------------------------------ run

    def run(self, max_inflight_per_instance: int = 4,
            timeout_s: float = 300.0) -> Dict[str, List[Any]]:
        """Pump until every source is exhausted and every stage drained.
        → {sink_name: [items]} (arrival order)."""
        import cloudpickle
        import time as _time

        own_rt = not rt.is_initialized()
        if own_rt:
            rt.init()
        actor_cls = rt.remote(max_restarts=1)(_OperatorActor)
        order = self._toposort()
        for st in order:
            if st.op_factory is not None:
                blob = cloudpickle.dumps(st.op_factory)
                st.handles = [actor_cls.remote(blob)
                              for _ in range(st.parallelism)]
                st.inflight = {i: [] for i in range(st.parallelism)}
                st.rr = itertools.count()

        deadline = _time.monotonic() + timeout_s
        try:
            while True:
                progressed = self._pump(order, max_inflight_per_instance)
                if self._finished(order):
                    break
                if not progressed:
                    done_any = self._drain(order, max_inflight_per_instance,
                                           block=True)
                    if _time.monotonic() > deadline:
                        raise TimeoutError("dataflow made no progress "
                                           f"within {timeout_s}s")
                    if not done_any:
                        _time.sleep(0.005)
            return {s.name: s.results for s in order if s.is_sink}
        finally:
            for st in order:
                for h in st.handles:
                    rt.kill(h)
            if own_rt:
                rt.shutdown()

    # ------------------------------------------------------------ internals

    def _toposort(self) -> List[Stage]:
        indeg = {s.name: len(s.upstreams) for s in self.stages.values()}
        queue = collections.deque(
            s for s in self.stages.values() if indeg[s.name] == 0)
        out: List[Stage] = []
        while queue:
            s = queue.popleft()
            out.append(s)
            for d in s.downstreams:
                indeg[d.name] -= 1
                if indeg[d.name] == 0:
                    queue.append(d)
        if len(out) != len(self.stages):
            raise ValueError("dataflow graph has a cycle")
        return out

    def _route(self, st: Stage, item: Any) -> List[int]:
        kind, fn = st.partitioning
        if kind == "broadcast":
            return list(range(st.parallelism))
        if kind == "keyed":
            return [hash(fn(item)) % st.parallelism]
        return [next(st.rr) % st.parallelism]

    def _emit(self, st: Stage, item: Any) -> None:
        """Send one item into stage ``st`` (or record at a sink)."""
        if st.is_sink:
            st.results.append(item)
            return
        for i in self._route(st, item):
            ref = st.handles[i].process.remote(item)
            st.inflight[i].append(ref)

    def _has_credit(self, st: Stage, cap: int) -> bool:
        if st.is_sink:
            return True
        return all(len(v) < cap for v in st.inflight.values())

    def _forward(self, st: Stage, out: Any) -> None:
        if out is None:
            return
        items = out if isinstance(out, list) else [out]
        for d in st.downstreams:
            for it in items:
                self._emit(d, it)

    def _drain(self, order: List[Stage], cap: int,
               block: bool = False) -> bool:
        """Collect finished calls, forward outputs. → any completions?

        Backpressure propagates stage to stage: a stage whose downstream
        is at its credit limit is NOT drained — its results stay parked in
        its (bounded) inflight lists until the downstream frees credit, so
        memory stays bounded along the whole chain, not just at sources.
        """
        refs = [ref for st in order for lst in st.inflight.values()
                for ref in lst]
        if not refs:
            return False
        if block:
            rt.wait(refs, num_returns=1, timeout=1.0)
        done, _ = rt.wait(refs, num_returns=len(refs), timeout=0.0)
        done = set(done)
        any_done = False
        for st in order:
            if not all(self._has_credit(d, cap) for d in st.downstreams):
                continue   # downstream saturated: hold our results
            for i in list(st.inflight):
                remaining = []
                for ref in st.inflight[i]:
                    if ref in done:
                        out = rt.get(ref)
                        self._forward(st, out)
                        any_done = True
                    else:
                        remaining.append(ref)
                st.inflight[i] = remaining
        return any_done

    def _pump(self, order: List[Stage], cap: int) -> bool:
        progressed = self._drain(order, cap)
        # pull from sources while every downstream has credit (backpressure)
        for st in order:
            if not st.is_source or st.closed:
                continue
            while all(self._has_credit(d, cap) for d in st.downstreams):
                try:
                    item = next(st.source_iter)
                except StopIteration:
                    st.closed = True
                    break
                for d in st.downstreams:
                    self._emit(d, item)
                progressed = True
        # end-of-stream: flush stages whose upstreams are fully done
        for st in order:
            if (st.is_source or st.is_sink or st.flushed
                    or not self._upstreams_done(st)):
                continue
            if any(st.inflight[i] for i in st.inflight):
                continue  # wait for own in-flight work first
            for h in st.handles:
                out = rt.get(h.flush.remote(), timeout=60.0)
                self._forward(st, out)
            st.flushed = True
            progressed = True
        return progressed

    def _upstreams_done(self, st: Stage) -> bool:
        for u in st.upstreams:
            if u.is_source:
                if not u.closed:
                    return False
            elif not u.flushed or any(u.inflight[i] for i in u.inflight):
                return False
        return True

    def _finished(self, order: List[Stage]) -> bool:
        for st in order:
            if st.is_source and not st.closed:
                return False
            if st.inflight and any(st.inflight[i] for i in st.inflight):
                return False
            if (not st.is_source and not st.is_sink and not st.flushed):
                return False
        return True
