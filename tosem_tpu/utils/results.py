"""CSV result writer with the study-compatible schema.

The reference's analysis layer (SURVEY §1 L8) consumes experiment CSVs with a
stable schema of test/bench id, project, metric, value (e.g.
``RQs/RQ3/tests_correlate_rq3.csv``, ``RQs/RQ4/tests_methods_v3.csv``). Every
benchmark and experiment in this framework funnels its output through this
writer so the study's downstream analysis keeps working against TPU runs.
"""
from __future__ import annotations

import csv
import json
import os
import time
from dataclasses import dataclass, asdict, field
from typing import Any, Dict, Iterable, List, Optional

SCHEMA = [
    "timestamp",     # unix seconds
    "project",       # which subsystem produced the row (ops, parallel, models…)
    "config",        # experiment config name (gemm, conv_sweep, allreduce…)
    "bench_id",      # unique id of the individual measurement
    "metric",        # metric name (gflops, bus_bw_gbps, step_time_ms…)
    "value",         # float value
    "unit",          # unit string
    "device",        # tpu | cpu | gpu
    "n_devices",     # number of participating devices
    "extra",         # JSON blob for shapes/dtypes/anything else
]


@dataclass
class ResultRow:
    project: str
    config: str
    bench_id: str
    metric: str
    value: float
    unit: str
    device: str = "tpu"
    n_devices: int = 1
    extra: Dict[str, Any] = field(default_factory=dict)
    timestamp: float = 0.0

    def to_csv_dict(self) -> Dict[str, Any]:
        d = asdict(self)
        d["timestamp"] = self.timestamp or time.time()
        d["extra"] = json.dumps(self.extra, sort_keys=True)
        return {k: d[k] for k in SCHEMA}


class ResultWriter:
    """Appends :class:`ResultRow`\\ s to a CSV file, creating the header once."""

    def __init__(self, path: str):
        self.path = path
        self._rows: List[ResultRow] = []

    def add(self, row: ResultRow) -> None:
        self._rows.append(row)

    def add_many(self, rows: Iterable[ResultRow]) -> None:
        self._rows.extend(rows)

    def flush(self) -> None:
        if not self._rows:
            return
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        write_header = not os.path.exists(self.path) or os.path.getsize(self.path) == 0
        with open(self.path, "a", newline="") as f:
            w = csv.DictWriter(f, fieldnames=SCHEMA)
            if write_header:
                w.writeheader()
            for r in self._rows:
                w.writerow(r.to_csv_dict())
        self._rows.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.flush()


def read_results(path: str) -> List[Dict[str, Any]]:
    with open(path, newline="") as f:
        rows = list(csv.DictReader(f))
    for r in rows:
        r["value"] = float(r["value"])
        r["n_devices"] = int(r["n_devices"])
        r["extra"] = json.loads(r["extra"]) if r.get("extra") else {}
    return rows
