"""Benchmark timing harness for jitted TPU computations.

Equivalent role to the reference's CUDA-event timing around kernel launches
(e.g. Apollo's ``modules/perception/inference/utils/gemm.cu`` measured under
nvprof) and Ray's ``python/ray/ray_perf.py:74`` ``timeit`` harness. On TPU the
only correct recipe is: jit, run once to compile, then wall-time loops ended
with ``block_until_ready`` (dispatch is async).
"""
from __future__ import annotations

import statistics
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


class MeasurementBelowNoiseFloor(RuntimeError):
    """The timed kernel cannot be resolved against host/sync noise."""


@dataclass
class BenchStats:
    name: str
    iters: int
    mean_s: float
    std_s: float
    min_s: float
    p50_s: float

    @property
    def mean_ms(self) -> float:
        return self.mean_s * 1e3

    def throughput(self, work_per_iter: float) -> float:
        """work units / second based on min time (the noise-free estimator:
        sync round-trip jitter only ever inflates samples, never deflates)."""
        return work_per_iter / self.min_s if self.min_s > 0 else float("inf")


def _sync(x: Any) -> None:
    """Force real device synchronisation.

    ``block_until_ready`` alone is not trustworthy on remote-tunnelled
    platforms (observed: it returns immediately under axon), so we fetch one
    scalar element per leaf to the host. Device programs execute in order, so
    fetching from the *last* enqueued output drains the whole queue.
    """
    for v in jax.tree_util.tree_leaves(x):
        if isinstance(v, jax.Array):
            if v.size:
                jax.device_get(v.ravel()[0])
            else:
                v.block_until_ready()


def _timed_batch(fn: Callable[[], Any], n: int) -> float:
    t0 = time.perf_counter()
    out = None
    for _ in range(n):
        out = fn()
    _sync(out)
    return time.perf_counter() - t0


def time_fn(fn: Callable[[], Any], *, iters: int = 20, warmup: int = 3,
            name: str = "bench", inner: int = 0,
            target_sample_s: float = 50e-3) -> BenchStats:
    """Time ``fn`` (returning device arrays) with compile warmup.

    Uses differential batch timing: a sample enqueues ``inner`` calls
    back-to-back and syncs once; per-call time is
    ``(t_inner - min t_1)/(inner - 1)``, which cancels the per-sample sync
    round trip. On remote-tunnelled TPU platforms (axon) that round trip is
    tens of ms — orders of magnitude above kernel time — and
    ``block_until_ready`` alone does not even synchronise, so naive timing
    is wrong in both directions. ``inner=0`` auto-calibrates so each
    sample's pure compute is ~``target_sample_s``.
    """
    for _ in range(max(1, warmup)):
        _sync(fn())
    # t_N = N*k + R with R the (large, noisy) per-sample sync round trip.
    # Min-statistics differential: k = (min t_N - min t_1) / (N - 1) cancels
    # R without modelling it.
    t1_min = min(_timed_batch(fn, 1) for _ in range(3))
    t10_min = min(_timed_batch(fn, 10) for _ in range(2))
    k_est = max((t10_min - t1_min) / 9.0, 1e-8)
    if inner <= 0:
        inner = max(2, min(4000, int(round(target_sample_s / k_est))))
    inner = max(2, inner)
    samples = []
    for _ in range(iters):
        t = _timed_batch(fn, inner)
        samples.append(max(t - t1_min, 1e-9) / (inner - 1))
    return BenchStats(
        name=name,
        iters=iters,
        mean_s=statistics.fmean(samples),
        std_s=statistics.pstdev(samples) if len(samples) > 1 else 0.0,
        min_s=min(samples),
        p50_s=statistics.median(samples),
    )


@dataclass
class DeviceLoopBench:
    """On-device kernel timing via a chained ``lax.fori_loop``.

    Python-side dispatch over a remote-tunnelled platform costs ~0.1ms per
    call, swamping sub-ms kernels. This harness runs N op applications
    inside ONE compiled program, chained through a scalar extracted from
    each output and added to one operand scaled by a runtime-zero epsilon:
    numerics are exact (eps=0 at run time) but XLA cannot hoist the op out
    of the loop (eps is unknown at compile time), so all N executions
    really happen, serialised by the data dependence.
    """
    op: Callable[..., Any]       # op(*args) -> array
    args: tuple                  # device arrays
    perturb: int = 0             # which arg receives the +eps*s feedback

    def _loop_fn(self):
        from jax import lax
        op, perturb = self.op, self.perturb

        def run(n_iter, eps, *args):
            def body(i, s):
                ins = list(args)
                a = ins[perturb]
                ins[perturb] = a + (eps * s).astype(a.dtype)
                out = op(*ins)
                # the carry must consume EVERY output element — a single
                # element would let XLA dead-code-eliminate most of the op
                return jnp.mean(out.astype(jnp.float32))
            # dynamic trip count: ONE compiled program serves every n, so
            # growth probing never pays (or mis-measures) recompilation
            return lax.fori_loop(0, n_iter, body, jnp.float32(0.0))

        return jax.jit(run)

    def time(self, *, n_iter: int = 0, reps: int = 3,
             signal_s: float = 0.3, max_iter: int = 400_000) -> float:
        """Seconds per op execution (min over reps, dispatch cancelled).

        ``n_iter=0`` grows the loop count geometrically until total loop
        time clearly exceeds the per-dispatch round-trip noise (tens of ms
        on tunnelled platforms), so ``(t_n - t_1)/(n-1)`` is a clean
        kernel-time estimate even for micro-second kernels.
        """
        loop = self._loop_fn()
        eps = jax.device_put(jnp.zeros((), "float32"))

        def timed(n: int) -> float:
            nn = jnp.int32(n)
            t0 = time.perf_counter()
            _sync(loop(nn, eps, *self.args))
            return time.perf_counter() - t0

        timed(1)  # compile
        t1_min = min(timed(1) for _ in range(reps))
        auto = n_iter <= 0
        if auto:
            if t1_min >= 2 * signal_s:
                # slow kernel: one execution already dwarfs round-trip
                # noise, no need to grow the loop (saves ~30x wall clock)
                n_iter = 4
            else:
                n_iter = 64
                while n_iter < max_iter and timed(n_iter) - t1_min < signal_s:
                    n_iter *= 4
                n_iter = min(n_iter, max_iter)
        n_iter = max(n_iter, 2)
        while True:
            tn_min = min(timed(n_iter) for _ in range(reps))
            if tn_min > t1_min:
                return (tn_min - t1_min) / (n_iter - 1)
            # differential below the noise floor: never report a fantasy
            # number (the old 1e-9 clamp produced PFLOPS readings)
            if n_iter >= max_iter:
                raise MeasurementBelowNoiseFloor(
                    f"loop of {n_iter} executions is indistinguishable from "
                    f"sync noise (t1={t1_min * 1e3:.2f}ms)")
            if not auto:
                raise MeasurementBelowNoiseFloor(
                    f"n_iter={n_iter} too small to resolve this kernel "
                    "against sync noise; use n_iter=0 (auto)")
            n_iter = min(n_iter * 4, max_iter)


def chain_overhead(args: tuple, perturb: int = 0, *,
                   reps: int = 3) -> float:
    """Seconds/iter of the loop-chain bookkeeping alone (upper bound).

    The :class:`DeviceLoopBench` body adds ``eps*s`` to one operand and
    mean-reduces the output — O(elements) memory work per iteration
    that is negligible next to an O(n^3) matmul but not next to a small
    op. This times an *identity-op* loop (same perturb + reduce, no
    op), giving an upper bound on that overhead: in the real loop XLA
    may fuse the add into the op's operand read and the mean into its
    output, making the true overhead smaller. Consumers can report
    ``[t_raw - overhead, t_raw]`` as the honest bracket for small ops.
    """
    bench = DeviceLoopBench(op=lambda *xs: xs[perturb], args=args,
                            perturb=perturb)
    try:
        return bench.time(reps=reps)
    except MeasurementBelowNoiseFloor:
        return 0.0


def gflops(flop_count: float, seconds: float) -> float:
    return flop_count / seconds / 1e9 if seconds > 0 else float("inf")


def matmul_flops(m: int, n: int, k: int) -> float:
    return 2.0 * m * n * k


def conv2d_flops(n: int, h_out: int, w_out: int, c_out: int, kh: int, kw: int,
                 c_in: int) -> float:
    return 2.0 * n * h_out * w_out * c_out * kh * kw * c_in
