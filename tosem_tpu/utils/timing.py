"""Benchmark timing harness for jitted TPU computations.

Equivalent role to the reference's CUDA-event timing around kernel launches
(e.g. Apollo's ``modules/perception/inference/utils/gemm.cu`` measured under
nvprof) and Ray's ``python/ray/ray_perf.py:74`` ``timeit`` harness. On TPU the
only correct recipe is: jit, run once to compile, then wall-time loops ended
with ``block_until_ready`` (dispatch is async).
"""
from __future__ import annotations

import statistics
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax


@dataclass
class BenchStats:
    name: str
    iters: int
    mean_s: float
    std_s: float
    min_s: float
    p50_s: float

    @property
    def mean_ms(self) -> float:
        return self.mean_s * 1e3

    def throughput(self, work_per_iter: float) -> float:
        """work units / second based on mean time."""
        return work_per_iter / self.mean_s if self.mean_s > 0 else float("inf")


def _block(x: Any) -> None:
    jax.tree_util.tree_map(
        lambda v: v.block_until_ready() if hasattr(v, "block_until_ready") else v, x)


def time_fn(fn: Callable[[], Any], *, iters: int = 20, warmup: int = 3,
            name: str = "bench", inner: int = 1) -> BenchStats:
    """Time ``fn`` (returning device arrays) with compile warmup.

    ``inner`` repeats fn per timed sample (for very fast ops, time the batch
    and divide — same trick as ``ray_perf``'s loops).
    """
    for _ in range(max(1, warmup)):
        _block(fn())
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = None
        for _ in range(inner):
            out = fn()
        _block(out)
        samples.append((time.perf_counter() - t0) / inner)
    return BenchStats(
        name=name,
        iters=iters,
        mean_s=statistics.fmean(samples),
        std_s=statistics.pstdev(samples) if len(samples) > 1 else 0.0,
        min_s=min(samples),
        p50_s=statistics.median(samples),
    )


def gflops(flop_count: float, seconds: float) -> float:
    return flop_count / seconds / 1e9 if seconds > 0 else float("inf")


def matmul_flops(m: int, n: int, k: int) -> float:
    return 2.0 * m * n * k


def conv2d_flops(n: int, h_out: int, w_out: int, c_out: int, kh: int, kw: int,
                 c_in: int) -> float:
    return 2.0 * n * h_out * w_out * c_out * kh * kw * c_in
