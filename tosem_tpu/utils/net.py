"""Stdlib-only network probes shared by bench/driver preflights."""
from __future__ import annotations

import socket


def tunnel_alive(port: int = 8083, timeout: float = 2.0) -> bool:
    """Probe the axon relay's stateless port. The tunnel can drop for the
    whole box (relay stops listening); callers should fail fast rather
    than hang in the PJRT plugin's dial-retry loop."""
    s = socket.socket()
    s.settimeout(timeout)
    try:
        s.connect(("127.0.0.1", port))
        return True
    except OSError:
        return False
    finally:
        s.close()
