"""YAML experiment manifests.

Role model: NNI's yaml experiment config (validated in
``nni/experiment/config/``) and EfficientDet's ``--hparams=voc_config.yaml``
override pattern (``hparams_config.py``). A manifest names a config, a device,
and free-form parameter overrides; ``load_manifest`` merges it over defaults.
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

try:
    import yaml  # pyyaml ships with the baked-in stack (transformers dep)
    _HAVE_YAML = True
except Exception:  # pragma: no cover
    yaml = None
    _HAVE_YAML = False

import json


@dataclass
class Manifest:
    name: str
    device: str = "tpu"
    configs: list = field(default_factory=list)
    params: Dict[str, Any] = field(default_factory=dict)
    results_csv: str = "results/results.csv"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Manifest":
        known = {k: v for k, v in d.items() if k in cls.__dataclass_fields__}
        extra = {k: v for k, v in d.items() if k not in cls.__dataclass_fields__}
        m = cls(**known)
        m.params.update(extra)
        return m


def load_manifest(path: str) -> Manifest:
    with open(path) as f:
        text = f.read()
    if _HAVE_YAML:
        data = yaml.safe_load(text)
    else:  # yaml unavailable: accept JSON manifests
        data = json.loads(text)
    if not isinstance(data, dict):
        raise ValueError(f"manifest {path} must be a mapping")
    return Manifest.from_dict(data)


def merge_params(defaults: Dict[str, Any], overrides: Dict[str, Any]) -> Dict[str, Any]:
    out = copy.deepcopy(defaults)
    for k, v in overrides.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = merge_params(out[k], v)
        else:
            out[k] = v
    return out
