"""Typed flag registry + CLI parser.

Plays the role of the reference's absl-flags modules (DeepSpeech defines ~87
flags in ``training/deepspeech_training/util/flags.py`` and materialises them
into a global Config in ``util/config.py``; Ray uses ``ray_constants.py`` +
env-var-driven ``src/ray/common/ray_config_def.h``). This is a small
self-contained equivalent: typed definitions, ``--name=value`` / ``--name
value`` parsing, environment-variable overrides (``TOSEM_<NAME>``), and yaml
merge for experiment manifests.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


def _parse_bool(s: str) -> bool:
    if isinstance(s, bool):
        return s
    v = s.strip().lower()
    if v in ("1", "true", "yes", "on"):
        return True
    if v in ("0", "false", "no", "off"):
        return False
    raise ValueError(f"not a boolean: {s!r}")


@dataclass
class _Flag:
    name: str
    default: Any
    help: str
    parser: Callable[[str], Any]
    choices: Optional[List[Any]] = None
    value: Any = None

    def set(self, raw: Any) -> None:
        val = self.parser(raw) if isinstance(raw, str) else raw
        if self.choices is not None and val not in self.choices:
            raise ValueError(
                f"--{self.name}={val!r} not in allowed choices {self.choices}"
            )
        self.value = val


class FlagSet:
    """A registry of typed flags with CLI/env/yaml binding."""

    def __init__(self, env_prefix: str = "TOSEM_"):
        self._flags: Dict[str, _Flag] = {}
        self._env_prefix = env_prefix

    # -- definitions -------------------------------------------------------
    def define_string(self, name, default=None, help=""):
        self._define(name, default, help, str)

    def define_integer(self, name, default=None, help=""):
        self._define(name, default, help, int)

    def define_float(self, name, default=None, help=""):
        self._define(name, default, help, float)

    def define_bool(self, name, default=False, help=""):
        self._define(name, default, help, _parse_bool)

    def define_list(self, name, default=None, help=""):
        self._define(name, list(default or []), help,
                     lambda s: [t for t in s.split(",") if t])

    def define_enum(self, name, default, choices, help=""):
        self._define(name, default, help, str, choices=list(choices))

    def _define(self, name, default, help, parser, choices=None):
        if name in self._flags:
            raise ValueError(f"flag {name!r} already defined")
        f = _Flag(name=name, default=default, help=help, parser=parser,
                  choices=choices)
        f.value = default
        self._flags[name] = f

    # -- access ------------------------------------------------------------
    def __getattr__(self, name: str) -> Any:
        flags = object.__getattribute__(self, "_flags")
        if name in flags:
            return flags[name].value
        raise AttributeError(name)

    def __contains__(self, name: str) -> bool:
        return name in self._flags

    def get(self, name: str, default: Any = None) -> Any:
        f = self._flags.get(name)
        return default if f is None else f.value

    def set(self, name: str, value: Any) -> None:
        if name not in self._flags:
            raise KeyError(f"unknown flag {name!r}")
        self._flags[name].set(value)

    def as_dict(self) -> Dict[str, Any]:
        return {n: f.value for n, f in self._flags.items()}

    def reset(self) -> None:
        for f in self._flags.values():
            f.value = f.default

    # -- binding -----------------------------------------------------------
    def apply_env(self, environ=None) -> None:
        environ = os.environ if environ is None else environ
        for name, f in self._flags.items():
            key = self._env_prefix + name.upper()
            if key in environ:
                f.set(environ[key])

    def parse_args(self, argv: List[str]) -> List[str]:
        """Parse ``--name=value`` / ``--name value`` / ``--nobool``.

        Returns leftover (positional) args. Unknown flags raise.
        """
        leftover: List[str] = []
        i = 0
        while i < len(argv):
            arg = argv[i]
            if not arg.startswith("--"):
                leftover.append(arg)
                i += 1
                continue
            body = arg[2:]
            if "=" in body:
                name, raw = body.split("=", 1)
                self._require(name).set(raw)
            elif body in self._flags and isinstance(self._flags[body].default, bool):
                self._flags[body].set(True)
            elif body.startswith("no") and body[2:] in self._flags and isinstance(
                    self._flags[body[2:]].default, bool):
                self._flags[body[2:]].set(False)
            else:
                if i + 1 >= len(argv):
                    raise ValueError(f"flag --{body} missing value")
                self._require(body).set(argv[i + 1])
                i += 1
            i += 1
        return leftover

    def apply_mapping(self, mapping: Dict[str, Any]) -> None:
        for k, v in mapping.items():
            self.set(k, v)

    def _require(self, name: str) -> _Flag:
        if name not in self._flags:
            raise ValueError(f"unknown flag --{name}")
        return self._flags[name]

    def usage(self) -> str:
        lines = []
        for n, f in sorted(self._flags.items()):
            extra = f" (choices: {f.choices})" if f.choices else ""
            lines.append(f"  --{n}={f.default!r}\t{f.help}{extra}")
        return "\n".join(lines)


GLOBAL_FLAGS = FlagSet()
