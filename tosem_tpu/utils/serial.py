"""Compact zero-copy pytree serialization (the capnp role).

NuPIC serializes algorithm state through Cap'n Proto schemas
(`nupic/serializable.py`, `src/nupic/proto/*.capnp`) so a trained
SP/TM restores bit-exactly and cheaply. The TPU-era equivalent of that
need is a flat, self-describing binary for **array pytrees**: a JSON
header (tree structure + per-leaf dtype/shape/offset) followed by the
raw little-endian buffers, 64-byte aligned so :func:`load_tree` can
return numpy views straight into the file's buffer (``zero_copy=True``)
— no per-leaf pickling, no copies, mmap-friendly, and safe to stash in
the shared-memory object store.

Format::

    magic b"TPT1" | u32 header_len | header_json | pad | buffers...

Header: ``{"tree": <nested lists/dicts with {"__leaf__": i} markers>,
"leaves": [{"dtype": "<f4", "shape": [..], "offset": N}, ...]}``.
Scalars (int/float/str/bool/None) are inlined in the tree.
"""
from __future__ import annotations

import json
import struct
from typing import Any, List, Tuple

import numpy as np

MAGIC = b"TPT1"
_ALIGN = 64


def _dtype_name(dt: np.dtype) -> str:
    # .name survives ml_dtypes (bfloat16 → 'bfloat16'); .str would record
    # the raw void layout ('<V2') and corrupt the round trip
    return np.dtype(dt).name


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        try:
            return np.dtype(getattr(ml_dtypes, name))
        except AttributeError:
            raise ValueError(
                f"unsupported leaf dtype {name!r}: not a numpy or "
                "ml_dtypes dtype") from None


def _plan(obj: Any, leaves: List[np.ndarray]):
    """Tree → JSON-able skeleton with leaf markers; collects arrays."""
    if isinstance(obj, dict):
        for k in obj:
            if not isinstance(k, str):
                raise TypeError(
                    f"dict keys must be strings (got {k!r}); non-string "
                    "keys would be silently stringified on round-trip")
        return {"__map__": {k: _plan(v, leaves)
                            for k, v in sorted(obj.items())}}
    if isinstance(obj, (list, tuple)):
        kind = "__list__" if isinstance(obj, list) else "__tuple__"
        return {kind: [_plan(v, leaves) for v in obj]}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return {"__scalar__": obj}
    # order="C" forces contiguity WITHOUT ascontiguousarray's 0-d→(1,)
    # promotion (which silently corrupted scalar-leaf shapes)
    arr = np.asarray(obj, order="C")
    if not arr.dtype.isnative:
        # dtype *names* don't carry byte order ('>f4'.name == 'float32'),
        # so normalize to native order rather than reject at dump time
        arr = arr.astype(arr.dtype.newbyteorder("="))
    leaves.append(arr)
    return {"__leaf__": len(leaves) - 1}


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def dump_tree(tree: Any) -> bytes:
    leaves: List[np.ndarray] = []
    skeleton = _plan(tree, leaves)
    offset = 0
    table = []
    for arr in leaves:
        offset = _align(offset)
        name = _dtype_name(arr.dtype)
        # validate at DUMP time that the recorded name loads back to the
        # same dtype — otherwise a blob that saves cleanly (e.g. unicode
        # leaves, dtype name 'str224') could never be loaded
        try:
            resolved = _resolve_dtype(name)
        except ValueError:
            resolved = None
        if resolved != arr.dtype:
            raise TypeError(
                f"unserializable leaf dtype {arr.dtype!r} (name {name!r} "
                "does not round-trip); supported: numeric numpy and "
                "ml_dtypes leaves")
        table.append({"dtype": name,
                      "shape": list(arr.shape), "offset": offset})
        offset += arr.nbytes
    header = json.dumps({"tree": skeleton, "leaves": table},
                        separators=(",", ":")).encode()
    prefix_len = len(MAGIC) + 4 + len(header)
    data_start = _align(prefix_len)
    out = bytearray(data_start + offset)
    out[:4] = MAGIC
    struct.pack_into("<I", out, 4, len(header))
    out[8:8 + len(header)] = header
    for arr, meta in zip(leaves, table):
        start = data_start + meta["offset"]
        out[start:start + arr.nbytes] = arr.tobytes()
    return bytes(out)


def _rebuild(node: Any, leaves: List[np.ndarray]):
    if "__map__" in node:
        return {k: _rebuild(v, leaves) for k, v in node["__map__"].items()}
    if "__list__" in node:
        return [_rebuild(v, leaves) for v in node["__list__"]]
    if "__tuple__" in node:
        return tuple(_rebuild(v, leaves) for v in node["__tuple__"])
    if "__scalar__" in node:
        return node["__scalar__"]
    return leaves[node["__leaf__"]]


def load_tree(blob: bytes, *, zero_copy: bool = True) -> Any:
    """Parse a :func:`dump_tree` blob. ``zero_copy=True`` returns
    read-only numpy views into ``blob``; pass False for owned copies
    (needed if the caller will mutate leaves or outlive the buffer)."""
    if blob[:4] != MAGIC:
        raise ValueError("not a TPT1 pytree blob")
    (header_len,) = struct.unpack_from("<I", blob, 4)
    header = json.loads(blob[8:8 + header_len].decode())
    data_start = _align(8 + header_len)
    mv = memoryview(blob)
    leaves: List[np.ndarray] = []
    for meta in header["leaves"]:
        dtype = _resolve_dtype(meta["dtype"])
        shape = tuple(meta["shape"])
        start = data_start + meta["offset"]
        nbytes = dtype.itemsize * int(np.prod(shape)) if shape else \
            dtype.itemsize
        arr = np.frombuffer(mv[start:start + nbytes], dtype=dtype)
        arr = arr.reshape(shape)
        if not zero_copy:
            arr = arr.copy()
        leaves.append(arr)
    return _rebuild(header["tree"], leaves)


def save_tree(tree: Any, path: str) -> int:
    blob = dump_tree(tree)
    with open(path, "wb") as f:
        f.write(blob)
    return len(blob)


def open_tree(path: str, *, zero_copy: bool = True) -> Any:
    """mmap the file and rebuild; with ``zero_copy`` the leaves are views
    over the mapping (the capnp read-without-parse property)."""
    import mmap
    with open(path, "rb") as f:
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    return load_tree(mm, zero_copy=zero_copy)
