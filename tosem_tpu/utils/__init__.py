from tosem_tpu.utils.flags import FlagSet, GLOBAL_FLAGS
from tosem_tpu.utils.results import ResultWriter, ResultRow
from tosem_tpu.utils.timing import (BenchStats, DeviceLoopBench,
                                    MeasurementBelowNoiseFloor,
                                    time_fn, gflops)
