"""Roofline annotation + result-CSV round-tripping, shared by bench entry
points.

The reference ships its utilization accounting inside each benchmark driver
(e.g. ``modules/perception/inference/utils/gemm.cu:107-121`` hardcodes the
device peak next to the cuBLAS call); here the peaks and the roofline
classification live in ONE module so ``bench.py``, the CLI runners, and the
opportunistic TPU-capture harness all agree on what "MFU" means.

Peak assumptions (documented in BASELINE.md "TPU peak assumptions"):
v5e MXU peak 197 TFLOPS bf16; fp32 executes as 6-pass bf16 emulation at
HIGHEST precision -> 197/6 ~= 32.8 TFLOPS effective; HBM ~819 GB/s.

``read_rows`` parses a results CSV (``tosem_tpu.utils.results.SCHEMA``)
back into :class:`ResultRow` objects so reports can be rebuilt from disk —
a capture interrupted by a tunnel flap loses a process, not the report.
"""
from __future__ import annotations

import csv
import json
from typing import Iterable, List, Optional

from tosem_tpu.utils.results import ResultRow, SCHEMA

PEAK_BF16_GFLOPS = 197_000.0             # v5e MXU peak, bf16
PEAK_FP32_GFLOPS = PEAK_BF16_GFLOPS / 6  # 6-pass bf16 emulation (HIGHEST)
PEAK_INT8_GOPS = 394_000.0               # v5e MXU integer path (2x bf16)
PEAK_HBM_GBPS = 819.0                    # v5e HBM bandwidth


def annotate_roofline(row: ResultRow) -> None:
    """Attach roofline utilization to a result row in place.

    Every row gets ``bound`` in {compute, memory} — which roofline term
    dominates its ideal time — plus the MATCHING utilization (MFU against
    the MXU peak, or MBU against HBM). Reporting MFU on a memory-bound
    1x1 conv makes a correct kernel look broken; reporting MBU on a
    compute-bound GEMM hides a slow one. Rows that report GFLOPS also
    carry ``bytes`` so both terms are computable.
    """
    unit = row.unit.lower()
    dtype = str(row.extra.get("dtype", ""))
    if unit == "gflops":
        if "float32" in dtype:
            peak = PEAK_FP32_GFLOPS
        elif "int8" in dtype:
            peak = PEAK_INT8_GOPS
        else:
            peak = PEAK_BF16_GFLOPS
        row.extra["mfu"] = round(row.value / peak, 4)
        nbytes = row.extra.get("bytes")
        if nbytes and row.value > 0:
            flops = row.value * 1e9  # per second
            sec_per_call = None
            if row.extra.get("mean_ms"):
                sec_per_call = row.extra["mean_ms"] / 1e3
            elif row.extra.get("time_us"):
                sec_per_call = row.extra["time_us"] / 1e6
            if sec_per_call:
                eff_gbps = nbytes / sec_per_call / 1e9
                row.extra["mbu"] = round(eff_gbps / PEAK_HBM_GBPS, 4)
                # which term dominates the ROOFLINE (ideal) time —
                # computable only with a per-call time (per-call flops vs
                # per-call bytes; mixing rates and totals would classify
                # arbitrarily)
                total_flops = flops * sec_per_call
                t_compute = total_flops / (peak * 1e9)
                t_memory = nbytes / (PEAK_HBM_GBPS * 1e9)
                row.extra["bound"] = ("memory" if t_memory > t_compute
                                      else "compute")
        else:
            row.extra["bound"] = "compute"
    elif unit == "gb/s":
        row.extra["mbu"] = round(row.value / PEAK_HBM_GBPS, 4)
        row.extra["bound"] = "memory"


def read_rows(path: str,
              min_timestamp: float = 0.0) -> List[ResultRow]:
    """Parse a results CSV back into rows (newest-last, file order)."""
    rows: List[ResultRow] = []
    with open(path, newline="") as f:
        for rec in csv.DictReader(f):
            # a subprocess killed mid-flush leaves a torn last line:
            # skip any record that doesn't parse, never poison the file
            try:
                ts = float(rec["timestamp"])
                if ts < min_timestamp:
                    continue
                try:
                    extra = json.loads(rec.get("extra") or "{}")
                except json.JSONDecodeError:
                    extra = {}
                rows.append(ResultRow(
                    project=rec["project"] or "", config=rec["config"] or "",
                    bench_id=rec["bench_id"] or "",
                    metric=rec["metric"] or "",
                    value=float(rec["value"]), unit=rec["unit"] or "",
                    device=rec["device"] or "",
                    n_devices=int(float(rec["n_devices"] or 1)),
                    extra=extra if isinstance(extra, dict) else {},
                    timestamp=ts))
            except (TypeError, ValueError, KeyError):
                continue
    return rows


def latest_rows(rows: Iterable[ResultRow]) -> List[ResultRow]:
    """Keep only the newest row per (config, bench_id, metric) key.

    Captures append; reruns of a leg supersede their earlier rows so a
    report built from the file reflects the freshest measurement of each
    quantity without losing file history.
    """
    best = {}
    for r in rows:
        key = (r.config, r.bench_id, r.metric)
        if key not in best or r.timestamp >= best[key].timestamp:
            best[key] = r
    return list(best.values())
