#!/usr/bin/env python
"""Headline benchmark: north-star config 1 (single-op GEMM microbench).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The anchor is the reference's cuBLAS GEMM path
(``modules/perception/inference/utils/gemm.cu:107-121`` — ``cublasSgemm``):
a V100-class part sustains ~13 TFLOPS fp32 on a 1024x1024x1024 SGEMM, so
``vs_baseline`` is measured GFLOPS / 13000. Timing uses the on-device
chained-loop harness (``tosem_tpu.utils.timing.DeviceLoopBench``) so the
number is pure kernel time even over a remote-tunnelled TPU.
"""
from __future__ import annotations

import json
import sys

BASELINE_GFLOPS = 13000.0  # cublasSgemm 1024^3 fp32, V100-class (BASELINE.md)


def main() -> None:
    from tosem_tpu.ops.gemm import GemmSpec, gemm_bench

    spec = GemmSpec(1024, 1024, 1024, dtype="float32", precision="float32")
    stats, row = gemm_bench(spec)
    print(json.dumps({
        "metric": "gemm_1024x1024x1024_fp32_gflops",
        "value": round(row.value, 2),
        "unit": "GFLOPS",
        "vs_baseline": round(row.value / BASELINE_GFLOPS, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
