"""Quickstart: hyperparameter search with trials as runtime actors.

ASHA early stopping over a TPE suggester — the Tune/NNI workflow in ten
lines. Hermetic CPU by default; set TOSEM_EXAMPLE_PLATFORM for hardware.

    python examples/quickstart_hpo.py
"""
import _bootstrap

_bootstrap.setup()

from tosem_tpu import tune                                    # noqa: E402


def trainable(config):
    """Generator trainable: yield one metrics dict per iteration."""
    x, lr = config["x"], config["lr"]
    loss = (x - 2.0) ** 2 + 1.0
    for _ in range(30):
        loss *= (1.0 - min(lr, 0.9) * 0.3)
        yield {"loss": loss}


def main():
    analysis = tune.run(
        trainable,
        {"x": tune.uniform(-5, 5), "lr": tune.loguniform(1e-3, 1.0)},
        metric="loss", mode="min", num_samples=12,
        scheduler=tune.ASHAScheduler(max_t=30, grace_period=3),
        search_alg=tune.TPESearch(seed=0),
        max_concurrent=4)
    print(f"best loss={-analysis.best_trial.best_score:.5f} "
          f"config={analysis.best_config}")


if __name__ == "__main__":
    main()
