"""Quickstart: cross-language surface + generated client stubs.

Boot the JSON-wire gateway, bridge the durable experiment manager onto
it (the nnictl surface), then do what a non-Python team would do:

1. introspect the LIVE gateway over the wire,
2. generate client stubs for C++ / Java / Node (the SWIG role,
   ``tosem_tpu.cluster.stubgen``),
3. compile the generated C++ stub with g++ and drive a whole HPO
   experiment through it — create, start, poll, results — without a
   line of Python on the client side.

    python examples/quickstart_xlang.py
"""
import _bootstrap

_bootstrap.setup()

import json                                                   # noqa: E402
import os                                                     # noqa: E402
import shutil                                                 # noqa: E402
import subprocess                                             # noqa: E402
import tempfile                                               # noqa: E402
import time                                                   # noqa: E402


def trial(config):
    x = config["x"]
    for i in range(3):
        yield {"loss": (x - 2.0) ** 2 + 1.0 / (i + 1)}


def main():
    from tosem_tpu.cluster.stubgen import describe_remote, write_stubs
    from tosem_tpu.cluster.xlang import XLangGateway
    from tosem_tpu.tune.experiment import ExperimentManager

    workdir = tempfile.mkdtemp(prefix="xlang_quickstart_")
    mgr = ExperimentManager(path=os.path.join(workdir, "experiments.db"))
    gw = XLangGateway()
    gw.bridge_experiments(mgr)
    print(f"gateway at {gw.address} with methods:")

    # 1-2: wire introspection -> stub families
    methods = describe_remote(gw.address)
    for m in methods:
        print(f"  {m.name}({', '.join(m.params)})")
    stub_dir = _bootstrap.artifact_path("stubs")
    paths = write_stubs(methods, stub_dir)
    for lang, p in sorted(paths.items()):
        print(f"generated {lang}: {p}")

    # 3: compile the C++ stub and run the whole experiment through it
    # (skipped gracefully on images without a C++ toolchain — steps 1-2
    # already proved introspection + generation)
    if shutil.which("g++") is None:
        print("g++ not found; skipping the compile-and-drive leg")
        gw.close()
        return
    host, port = gw.address.split(":")
    main_cpp = os.path.join(workdir, "drive.cpp")
    with open(main_cpp, "w") as f:
        f.write(f'''
#include "TosemXlangClient.hpp"
#include <unistd.h>
#include <cstdio>
#include <string>
int main() {{
  TosemXlangClient c("{host}", "{port}");
  std::string spec = R"({{"name": "demo",
    "trainable": "quickstart_xlang:trial",
    "space": {{"x": {{"type": "uniform", "low": -4.0, "high": 6.0}}}},
    "metric": "loss", "mode": "min", "num_samples": 4,
    "max_iterations": 3}})";
  if (!TosemXlangClient::ok(c.experiment_create(spec))) return 1;
  if (!TosemXlangClient::ok(c.experiment_start("\\"demo\\""))) return 2;
  for (int i = 0; i < 600; ++i) {{
    std::string st = c.experiment_status("\\"demo\\"");
    if (st.find("\\"done\\"") != std::string::npos ||
        st.find("\\"failed\\"") != std::string::npos) break;
    usleep(200 * 1000);
  }}
  std::string res = c.experiment_results("\\"demo\\"");
  std::printf("%s\\n", res.c_str());
  return TosemXlangClient::ok(res) ? 0 : 3;
}}
''')
    binary = os.path.join(workdir, "drive")
    subprocess.run(["g++", "-std=c++17", "-O1", main_cpp, "-o", binary,
                    f"-I{stub_dir}"], check=True, timeout=240)
    t0 = time.time()
    proc = subprocess.run([binary], capture_output=True, text=True,
                          timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    trials = payload["result"]
    best = min((t["best_score"] for t in trials
                if t.get("best_score") is not None), default=None)
    assert best is not None and best < 36.0
    print(f"C++ stub drove a {len(trials)}-trial experiment end-to-end "
          f"in {time.time() - t0:.1f}s; best loss {best:.3f}")
    gw.close()


if __name__ == "__main__":
    main()
