"""Quickstart: data-parallel ResNet training on a device mesh.

Defaults to a hermetic 8-virtual-device CPU mesh so it runs on any box;
set ``TOSEM_EXAMPLE_PLATFORM=tpu`` (or your accelerator) to span real
chips with the SAME program.

    python examples/quickstart_train.py
"""
import _bootstrap

_bootstrap.setup()

import jax                                                    # noqa: E402
import optax                                                  # noqa: E402

from tosem_tpu.data import cifar_like_batches                 # noqa: E402
from tosem_tpu.models import resnet18_ish                     # noqa: E402
from tosem_tpu.parallel.mesh import default_mesh              # noqa: E402
from tosem_tpu.train import (create_train_state,              # noqa: E402
                             make_train_step, shard_batch)
from tosem_tpu.train.trainer import classification_loss      # noqa: E402


def main():
    mesh = default_mesh("dp")
    print(f"devices: {len(jax.devices())} x {jax.devices()[0].platform}")
    model = resnet18_ish(num_classes=10, dtype=jax.numpy.float32)
    opt = optax.adamw(1e-3)
    ts = create_train_state(model, jax.random.PRNGKey(0), opt)
    step = make_train_step(model, opt, classification_loss, mesh=mesh)
    rng = jax.random.PRNGKey(1)
    for i, batch in enumerate(cifar_like_batches(32, steps=20)):
        rng, sub = jax.random.split(rng)
        ts, metrics = step(ts, shard_batch(batch, mesh), sub)
        if i % 5 == 0:
            print(f"step {i:3d} loss={float(metrics['loss']):.4f} "
                  f"acc={float(metrics['accuracy']):.3f}")
    print("done")


if __name__ == "__main__":
    main()
