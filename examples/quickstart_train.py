"""Quickstart: data-parallel ResNet training on a device mesh.

Runs anywhere: on a TPU slice the mesh spans real chips; on a CPU box
set ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (done below
when no accelerator is present) and the same program runs on 8 virtual
devices.

    python examples/quickstart_train.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))           # run from anywhere

if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax                                                    # noqa: E402

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import optax                                                  # noqa: E402

from tosem_tpu.data import cifar_like_batches                 # noqa: E402
from tosem_tpu.models import resnet18_ish                     # noqa: E402
from tosem_tpu.parallel.mesh import default_mesh              # noqa: E402
from tosem_tpu.train import (create_train_state,              # noqa: E402
                             make_train_step, shard_batch)
from tosem_tpu.train.trainer import classification_loss      # noqa: E402


def main():
    mesh = default_mesh("dp")
    print(f"devices: {len(jax.devices())} × {jax.devices()[0].platform}")
    model = resnet18_ish(num_classes=10, dtype=jax.numpy.float32)
    opt = optax.adamw(1e-3)
    ts = create_train_state(model, jax.random.PRNGKey(0), opt)
    step = make_train_step(model, opt, classification_loss, mesh=mesh)
    rng = jax.random.PRNGKey(1)
    for i, batch in enumerate(cifar_like_batches(32, steps=20)):
        rng, sub = jax.random.split(rng)
        ts, metrics = step(ts, shard_batch(batch, mesh), sub)
        if i % 5 == 0:
            print(f"step {i:3d} loss={float(metrics['loss']):.4f} "
                  f"acc={float(metrics['accuracy']):.3f}")
    print("done")


if __name__ == "__main__":
    main()
