"""Quickstart: gang scheduling + pluggable trial placement.

Two concurrent "distributed jobs" each need 3 of the 4 worker slots:
placement groups grant all-or-nothing (FIFO), so they serialize instead
of deadlocking. Then the same trainable runs through two training
services — in-process threads and isolated subprocesses — with no code
change to the trial.

    python examples/quickstart_gang.py
"""
import _bootstrap

_bootstrap.setup()

import threading
import time


def trial(config):
    x = config["x"]
    for i in range(3):
        yield {"loss": (x - 1.0) ** 2 + 1.0 / (i + 1)}


def main():
    import tosem_tpu.runtime as rt
    from tosem_tpu import tune
    from tosem_tpu.tune import LocalService, run_with_service

    rt.init(num_workers=4)
    f = rt.remote(lambda ms: (time.sleep(ms / 1e3), ms)[1])

    done = []

    def gang_job(tag):
        with rt.placement_group(3, timeout=60) as pg:
            refs = [f.options(placement_group=pg).remote(30)
                    for _ in range(3)]
            assert rt.get(refs) == [30, 30, 30]
            done.append(tag)

    threads = [threading.Thread(target=gang_job, args=(i,))
               for i in range(2)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    print(f"two 3-of-4 gangs completed without deadlock in "
          f"{time.perf_counter() - t0:.2f}s: {sorted(done)}")
    rt.shutdown()

    out = run_with_service(
        "quickstart_gang:trial", {"x": tune.uniform(-2.0, 4.0)},
        service=LocalService(max_concurrent=2), metric="loss",
        mode="min", num_samples=4, max_iterations=3,
        search_alg=tune.RandomSearch(), timeout_s=120)
    print(f"local service: best x={out['best_config']['x']:.3f} "
          f"loss={out['best_score']:.3f} "
          f"({sum(1 for t in out['trials'] if t['status'] == 'SUCCEEDED')}"
          f"/4 trials ok)")


if __name__ == "__main__":
    main()
