"""Quickstart: serve a model over HTTP with autoscaling replicas.

    python examples/quickstart_serve.py

Deploys a tiny classifier behind the router + HTTP ingress, posts a few
requests, and shows the autoscaler reacting to load.
"""
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))           # run from anywhere

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax                                                    # noqa: E402

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np                                            # noqa: E402

import tosem_tpu.runtime as rt                                # noqa: E402
from tosem_tpu.serve import (HttpIngress, Serve,              # noqa: E402
                             ServeAutoscaler, ServeScaleConfig)


class Classifier:
    """Replica backend: loads the model once, serves many requests."""

    def __init__(self):
        import jax.numpy as jnp
        from tosem_tpu.models import resnet18_ish
        self.model = resnet18_ish(num_classes=10,
                                  dtype=jnp.float32)
        self.vs = self.model.init(jax.random.PRNGKey(0))
        self.fwd = jax.jit(
            lambda vs, x: self.model.apply(vs, x)[0])

    def call(self, request):
        x = np.asarray(request["image"], np.float32)[None]
        logits = self.fwd(self.vs, x)
        return {"class": int(np.argmax(logits[0]))}


def main():
    rt.init(num_workers=2)
    try:
        serve = Serve()
        dep = serve.deploy("classify", Classifier, num_replicas=1)
        ingress = HttpIngress(serve)
        scaler = ServeAutoscaler(serve, default=ServeScaleConfig(
            max_replicas=3))
        scaler.run(interval=0.5)

        img = np.zeros((8, 8, 3), np.float32).tolist()
        for i in range(3):
            req = urllib.request.Request(
                f"{ingress.url}/classify",
                data=json.dumps({"image": img}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                print(f"request {i}: {json.loads(r.read())}")
        print(f"replicas: {dep.num_replicas}")
        scaler.stop()
        ingress.shutdown()
    finally:
        rt.shutdown()


if __name__ == "__main__":
    main()
