"""Quickstart: serve a model over HTTP with autoscaling replicas.

    python examples/quickstart_serve.py

Deploys a tiny classifier behind the router + HTTP ingress, posts a few
requests, and shows the autoscaler reacting to load. Hermetic CPU by
default; set TOSEM_EXAMPLE_PLATFORM for hardware.
"""
import json
import urllib.request

import _bootstrap

_bootstrap.setup()

import numpy as np                                            # noqa: E402

import tosem_tpu.runtime as rt                                # noqa: E402
from tosem_tpu.serve import (HttpIngress, Serve,              # noqa: E402
                             ServeAutoscaler, ServeScaleConfig)


class Classifier:
    """Replica backend: loads the model once, serves many requests."""

    def __init__(self):
        import jax
        import jax.numpy as jnp
        from tosem_tpu.models import resnet18_ish
        self.model = resnet18_ish(num_classes=10, dtype=jnp.float32)
        self.vs = self.model.init(jax.random.PRNGKey(0))
        self.fwd = jax.jit(lambda vs, x: self.model.apply(vs, x)[0])

    def call(self, request):
        x = np.asarray(request["image"], np.float32)[None]
        logits = self.fwd(self.vs, x)
        return {"class": int(np.argmax(logits[0]))}


def main():
    rt.init(num_workers=2)
    try:
        serve = Serve()
        dep = serve.deploy("classify", Classifier, num_replicas=1)
        # warm the replica BEFORE serving: actor boot + jit compile can
        # take the better part of a minute on a cold CPU box
        img = np.zeros((8, 8, 3), np.float32).tolist()
        serve.get_handle("classify").call({"image": img}, timeout=300)
        ingress = HttpIngress(serve, request_timeout=180)
        scaler = ServeAutoscaler(serve, default=ServeScaleConfig(
            max_replicas=3))
        scaler.run(interval=0.5)

        for i in range(3):
            req = urllib.request.Request(
                f"{ingress.url}/classify",
                data=json.dumps({"image": img}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=200) as r:
                print(f"request {i}: {json.loads(r.read())}")
        print(f"replicas: {dep.num_replicas}")
        scaler.stop()
        ingress.shutdown()
    finally:
        rt.shutdown()


if __name__ == "__main__":
    main()
