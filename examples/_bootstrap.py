"""Shared example bootstrap.

Examples default to the hermetic virtual-device CPU mesh so they run
identically on any box (the conftest recipe: env var AND jax.config,
because a sitecustomize may preset the platform — a preset
``JAX_PLATFORMS`` is machine config, not a user choice, so it is NOT
treated as opting in). To run an example on real hardware, set
``TOSEM_EXAMPLE_PLATFORM=tpu`` (or your accelerator).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def setup(virtual_devices: int = 8) -> None:
    explicit = os.environ.get("TOSEM_EXAMPLE_PLATFORM", "")
    if explicit not in ("", "cpu"):
        os.environ["JAX_PLATFORMS"] = explicit
        return                      # user chose real hardware: honor it
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{virtual_devices}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"     # force, not setdefault
    import jax
    jax.config.update("jax_platforms", "cpu")


def artifact_path(name: str) -> str:
    """Where an example drops a rendered artifact (kept out of the
    package tree; TOSEM_EXAMPLE_OUT overrides for CI temp dirs)."""
    base = os.environ.get("TOSEM_EXAMPLE_OUT") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "results", "examples")
    os.makedirs(base, exist_ok=True)
    return os.path.join(base, name)
