"""Quickstart: the AD driving loop on the deterministic runtime.

    python examples/quickstart_driving.py

Routing picks a lane route, then per frame: tracked detections →
constant-velocity prediction → scenario selection → corridor planning →
LQR/PID tracking — the Apollo-style stack rebuilt as batched JAX linear
algebra on the component runtime. A slow car drifts into the lane; the
scenario escalates and the planner dodges, then a full-lane wall forces
an emergency stop. Hermetic CPU by default; set TOSEM_EXAMPLE_PLATFORM
for hardware.
"""
import _bootstrap

_bootstrap.setup()

import numpy as np                                            # noqa: E402

from tosem_tpu.dataflow.components import (Component,         # noqa: E402
                                           ComponentRuntime)
from tosem_tpu.models import (Lane, LaneGraph,                # noqa: E402
                              RoutingComponent, TrackerComponent,
                              build_driving_pipeline)

# ----------------------------------------------------------------- route
graph = LaneGraph([
    Lane("on_ramp", 120.0, successors=["highway_a"]),
    Lane("highway_a", 400.0, successors=["highway_b"]),
    Lane("highway_b", 400.0, successors=[], half_width=1.6),
])

rtc = ComponentRuntime()
rtc.add(RoutingComponent(graph))
rtc.add(TrackerComponent(iou_threshold=0.1))
build_driving_pipeline(rtc, lane_half=1.6, frame_dt=1.0, horizon=2.0,
                       localize=True)

# the dreamview role: record frames for the dashboard's /drive panel
from tosem_tpu.obs.driveview import DriveViewRecorder  # noqa: E402

view = DriveViewRecorder(lane_half=1.6)
rtc.add(view)

frames = []


class Monitor(Component):
    def __init__(self):
        super().__init__("monitor", ["trajectory", "route", "control"])

    def proc(self, traj, route, ctl):
        frames.append((traj, route, ctl))
        scenario = traj["scenario"]
        fence = traj["stop_fence"]
        e = ctl["max_e_lat"] if ctl else float("nan")
        print(f"  scenario={scenario:<15} v_ref={traj['v_ref']:.1f} "
              f"stop_fence={fence:5.1f} max|e_lat|={e:.2f}")


rtc.add(Monitor())

print("== route")
rtc.writer("route_request")({"src": "on_ramp", "dst": "highway_b"})
rtc.run_until(0.5)

print("== driving")
det_w = rtc.writer("detections")
ego_w = rtc.writer("ego")
t = 0.5
# phase 1: clear road; phase 2: a car drifting into the lane ahead;
# phase 3: a full-lane wall inside braking distance
scenes = ([[]] * 2
          + [[[38.0, 1.4 - 0.4 * i, 42.0, 2.4 - 0.4 * i]]
             for i in range(3)]
          + [[[12.0, -1.6, 16.0, 1.6]]] * 2)
imu_w = rtc.writer("imu")
gnss_w = rtc.writer("gnss")
for i, boxes in enumerate(scenes):
    ego_w({"v": 8.0})
    # feed the localization branch so the drive view carries ego pose
    gnss_w({"pos": [8.0 * i, 0.0]})
    imu_w({"yaw_rate": 0.0, "accel": 0.0})
    det_w({"boxes": np.asarray(boxes, np.float32).reshape(-1, 4),
           "scores": np.ones((len(boxes),), np.float32)})
    t += 1.0
    rtc.run_until(t)

route = frames[-1][1]
assert route["route"] == ["on_ramp", "highway_a", "highway_b"]
scenarios = [f[0]["scenario"] for f in frames]
assert scenarios[0] == "LANE_FOLLOW"
assert "EMERGENCY_STOP" in scenarios
assert frames[-1][0]["stop_fence"] <= 11.0      # stops short of the wall
print(f"== drove {len(frames)} frames over "
      f"{route['length_m']:.0f} m of route; "
      f"scenario trace: {' -> '.join(dict.fromkeys(scenarios))}")

# render the final frame the way GET /drive would (server-side SVG)
from tosem_tpu.obs.driveview import render_scene_svg  # noqa: E402

svg = render_scene_svg(view.scene())
out = _bootstrap.artifact_path("driveview.html")
with open(out, "w") as f:
    f.write(f"<!doctype html><html><body>{svg}</body></html>")
assert "<svg" in svg and "polyline" in svg
print(f"== drive view rendered -> {out}")
